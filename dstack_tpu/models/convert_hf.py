"""HuggingFace checkpoint → dstack_tpu parameter pytree.

Bridges the serving/fine-tune paths to real released weights: point
``load_checkpoint`` at a ``save_pretrained`` directory (safetensors or
torch ``.bin`` shards) and get back ``(LlamaConfig, params)`` ready for
:func:`dstack_tpu.models.llama.forward`, the serve engine, and the
finetune driver.

Supported ``model_type``s: ``llama``, ``qwen2``, ``qwen3``,
``qwen3_moe``, ``mistral``, ``gemma``, ``gemma2``, ``gemma3``/
``gemma3_text`` (multimodal checkpoints load their text tower),
``mixtral``, ``phi3`` (fused qkv/gate_up projections are split on
load; a Phi-3 export round-trips as the equivalent mistral/llama
layout), ``gpt_oss`` (attention sinks, linear router with
softmax-over-top-k gates, fused biased experts with the clamped glu,
yarn truncate=false). Each maps onto :class:`LlamaConfig` family flags (qkv_bias /
sliding_window / norm_offset / softcaps / dual-theta rope / MoE) — the
architecture deltas live in the config, not in per-family model code.

The reference framework never loads weights itself (user containers do);
this module is part of the in-repo inference/training engine that makes
``type: service`` self-contained.

Layout notes:
- HF ``*_proj.weight`` is [out, in] (torch Linear); our kernels want
  [in, out] → transpose.
- HF llama-family checkpoints already use the rotate-half RoPE
  convention (no head permutation needed, unlike Meta's originals).
- Our layer stacks are scanned: every per-layer leaf gains a leading
  ``[n_layers, ...]`` dim.
"""

import json
import math
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dstack_tpu.models.llama import LlamaConfig
from dstack_tpu.models.llama import layer_windows as _layer_windows

__all__ = [
    "config_from_hf",
    "config_to_hf",
    "convert_state_dict",
    "export_state_dict",
    "load_checkpoint",
    "save_checkpoint",
]


def config_from_hf(hf: dict, dtype: Any = jnp.bfloat16) -> LlamaConfig:
    """HF ``config.json`` dict → :class:`LlamaConfig`."""
    mt = hf.get("model_type", "llama")
    if mt in ("gemma3", "llama4") and "text_config" in hf:
        # multimodal wrapper: the text tower's config is nested (the
        # vision tower is out of scope; load_checkpoint strips its
        # weights and the language_model prefix)
        hf = {**hf["text_config"], "model_type": f"{mt}_text"}
        mt = f"{mt}_text"
    hidden = hf["hidden_size"]
    n_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hidden // n_heads
    if hf.get("attention_bias") and mt not in (
        "qwen2", "qwen3", "qwen3_moe", "glm", "glm4", "gpt_oss"
    ):
        # q/k/v/o biases exist in the checkpoint but our llama/mistral
        # paths would silently drop them — refuse rather than mis-serve
        # (StarCoder2 spells its biases use_bias, handled in its branch)
        raise ValueError(
            f"{mt} checkpoint sets attention_bias=true, which this "
            "converter only supports for qwen2/qwen3/glm/glm4"
        )
    act = hf.get("hidden_act") or "silu"
    act_map = {
        "silu": "silu", "gelu_pytorch_tanh": "gelu_tanh", "relu2": "relu2"
    }
    if mt in ("gemma", "gemma2", "gemma3", "gemma3_text"):
        # Gemma configs historically say "gelu"/hidden_activation but
        # the models always use the tanh approximation
        act = "gelu_tanh"
    elif act not in act_map:
        raise ValueError(
            f"unsupported hidden_act {act!r} (supported: {sorted(act_map)})"
        )
    else:
        act = act_map[act]
    common = dict(
        hidden_act=act,
        vocab_size=hf["vocab_size"],
        hidden_size=hidden,
        n_layers=hf["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=hf.get("num_key_value_heads", n_heads),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        rope_scaling=_rope_scaling_from_hf(hf),
        dtype=dtype,
    )
    if mt == "llama":
        return LlamaConfig(**common)
    if mt == "qwen2":
        if hf.get("use_sliding_window"):
            # HF Qwen2 windows only layers >= max_window_layers — a
            # layering our periodic sliding_pattern can't express except
            # uniformly; refuse rather than silently run full attention
            if hf.get("max_window_layers", 0) not in (0, None):
                raise ValueError(
                    "qwen2 use_sliding_window with max_window_layers > 0 "
                    "is not supported"
                )
            common["sliding_window"] = hf.get("sliding_window") or 0
        # Qwen2 puts biases on q/k/v only (attention_bias is not in its
        # config; the arch always has them)
        return LlamaConfig(**common, qkv_bias=True)
    if mt == "qwen3":
        lt = hf.get("layer_types") or []
        if hf.get("use_sliding_window") or "sliding_attention" in lt:
            raise ValueError(
                "qwen3 sliding-attention layer_types are not supported"
            )
        return LlamaConfig(
            **common, qk_norm=True,
            qkv_bias=bool(hf.get("attention_bias")),
        )
    if mt == "qwen3_moe":
        # qwen3 attention (qk-norm) + sparse MoE MLP on every layer.
        # Checkpoints mixing dense and sparse layers can't be expressed
        # by the uniform layer stack — refuse rather than mis-run.
        if hf.get("mlp_only_layers") or hf.get("decoder_sparse_step", 1) != 1:
            raise ValueError(
                "qwen3_moe with dense layers (mlp_only_layers / "
                "decoder_sparse_step != 1) is not supported"
            )
        if hf.get("use_sliding_window"):
            raise ValueError("qwen3_moe sliding windows are not supported")
        common["intermediate_size"] = hf["moe_intermediate_size"]
        return LlamaConfig(
            **common,
            qk_norm=True,
            qkv_bias=bool(hf.get("attention_bias")),
            n_experts=hf["num_experts"],
            experts_per_token=hf.get("num_experts_per_tok", 8),
            router_renorm=bool(hf.get("norm_topk_prob", True)),
        )
    if mt == "gpt_oss":
        # OpenAI gpt-oss: alternating sliding/full attention with
        # learned attention sinks, a LINEAR router (bias + softmax over
        # the top-k logits), fused biased experts with the clamped glu
        # activation, yarn rope with truncate=false (HF
        # modeling_gpt_oss.py is the parity reference).
        lt = hf.get("layer_types") or []
        expected = [
            "sliding_attention" if i % 2 == 0 else "full_attention"
            for i in range(hf["num_hidden_layers"])
        ]
        if lt and lt != expected:
            raise ValueError(
                "gpt_oss layer_types deviate from the alternating "
                "sliding/full pattern; not supported"
            )
        return LlamaConfig(
            **common,
            qkv_bias=True,
            proj_bias=True,  # o-proj bias (dense-MLP biases N/A: MoE)
            attn_sinks=True,
            sliding_window=hf.get("sliding_window") or 0,
            # absent layer_types default to the alternating pattern in
            # HF GptOssConfig — a 0 fallback would window EVERY layer
            sliding_pattern=2,
            n_experts=hf["num_local_experts"],
            experts_per_token=hf.get("num_experts_per_tok", 4),
            router_topk_softmax=True,
            moe_bias=True,
            moe_act="oai_glu",
            act_limit=float(hf.get("swiglu_limit") or 7.0),
        )
    if mt == "mistral":
        return LlamaConfig(**common, sliding_window=hf.get("sliding_window") or 0)
    if mt == "phi3":
        if float(hf.get("partial_rotary_factor") or 1.0) != 1.0:
            raise ValueError("phi3 partial_rotary_factor != 1 is not supported")
        return LlamaConfig(**common, sliding_window=hf.get("sliding_window") or 0)
    if mt == "gemma":
        return LlamaConfig(
            **{**common, "tie_embeddings": True},
            norm_offset=True,
            embed_scale=True,
        )
    if mt == "gemma2":
        return LlamaConfig(
            **{**common, "tie_embeddings": True},
            norm_offset=True,
            embed_scale=True,
            post_norms=True,
            sliding_window=hf.get("sliding_window") or 0,
            sliding_pattern=2,  # even layers sliding, odd global
            attn_softcap=hf.get("attn_logit_softcapping") or 0.0,
            logit_softcap=hf.get("final_logit_softcapping") or 0.0,
            attn_scale=float(hf["query_pre_attn_scalar"]) ** -0.5
            if hf.get("query_pre_attn_scalar")
            else None,
        )
    if mt == "mixtral":
        return LlamaConfig(
            **common,
            n_experts=hf["num_local_experts"],
            experts_per_token=hf.get("num_experts_per_tok", 2),
            router_renorm=True,
        )
    if mt in ("gemma3", "gemma3_text"):
        sw = hf.get("sliding_window") or 0
        sw, pattern = _gemma3_pattern(hf, sw)
        return LlamaConfig(
            **{**common, "tie_embeddings": hf.get("tie_word_embeddings", True)},
            norm_offset=True,
            embed_scale=True,
            post_norms=True,
            qk_norm=True,
            sliding_window=sw,
            sliding_pattern=pattern,
            # dual rope: sliding layers rotate at the unscaled local
            # theta, global layers at rope_theta (+ linear scaling)
            rope_local_theta=hf.get("rope_local_base_freq", 10000.0),
            attn_scale=float(hf["query_pre_attn_scalar"]) ** -0.5
            if hf.get("query_pre_attn_scalar")
            else None,
        )
    if mt in ("llama4", "llama4_text"):
        return _llama4_config(hf, common)
    if mt in ("deepseek_v2", "deepseek_v3"):
        return _deepseek_config(hf, common, mt)
    if mt == "granite":
        # IBM Granite: llama skeleton + four scalar multipliers
        # (attention_multiplier IS the softmax scale; logits_scaling
        # divides, so it maps onto 1/logit_scale)
        ls = float(hf.get("logits_scaling") or 1.0)
        return LlamaConfig(
            **common,
            qkv_bias=False,
            attn_scale=float(hf.get("attention_multiplier") or 1.0),
            embed_multiplier=float(hf.get("embedding_multiplier") or 1.0),
            residual_multiplier=float(hf.get("residual_multiplier") or 1.0),
            logit_scale=(1.0 / ls) if ls != 1.0 else 0.0,
        )
    if mt == "starcoder2":
        # StarCoder2: plain LayerNorm with bias (stacked storage),
        # biases on every projection, gateless GELU MLP (c_fc/c_proj),
        # full-width rotate-half rope, tied embeddings
        return LlamaConfig(
            **{**common,
               "norm_eps": float(hf.get("norm_epsilon", 1e-5)),
               "tie_embeddings": bool(hf.get("tie_word_embeddings", True)),
               "sliding_window": hf.get("sliding_window") or 0},
            norm_type="layernorm_bias",
            mlp_gateless=True,
            qkv_bias=bool(hf.get("use_bias", True)),
            proj_bias=bool(hf.get("use_bias", True)),
        )
    if mt == "nemotron":
        # Nemotron/Minitron: LayerNorm1P ((1+w)·norm + b, stored stacked
        # [2, H]), gateless relu² MLP, rotate-half partial rotary
        return LlamaConfig(
            **{**common, "norm_eps": float(hf.get("norm_eps", 1e-5))},
            norm_type="layernorm1p",
            mlp_gateless=True,
            partial_rotary=float(hf.get("partial_rotary_factor") or 0.5),
        )
    if mt == "cohere":
        # Command-R: mean-centered LayerNorm, parallel attn+MLP block
        # over ONE shared input norm, interleaved rope, logit_scale,
        # optional per-head qk LayerNorm, tied embeddings
        return LlamaConfig(
            **{**common,
               "norm_eps": float(hf.get("layer_norm_eps", 1e-5)),
               # Cohere ties by default and omits the key when tied
               "tie_embeddings": bool(hf.get("tie_word_embeddings", True))},
            norm_type="layernorm",
            parallel_block=True,
            rope_interleaved=True,
            qk_norm=bool(hf.get("use_qk_norm")),
            logit_scale=float(hf.get("logit_scale", 0.0625)),  # HF default
        )
    if mt == "cohere2":
        # Command R7B: the Cohere layout (LayerNorm, parallel block,
        # logit_scale, interleaved rope) + a periodic sliding layout
        # where the full-attention layers carry NO rope at all — the
        # NoPE layers ARE the global layers, same period
        if hf.get("use_qk_norm"):
            raise ValueError("cohere2 use_qk_norm is not supported")
        # cohere2's default period is 4 (_gemma3_pattern would fall
        # back to Gemma3's 6 when both layout fields are absent)
        hf_l = {**hf}
        hf_l.setdefault("sliding_window_pattern", 4)
        sw, pattern = _gemma3_pattern(hf_l, hf.get("sliding_window") or 0)
        return LlamaConfig(
            **{**common,
               "norm_eps": float(hf.get("layer_norm_eps", 1e-5)),
               "tie_embeddings": bool(hf.get("tie_word_embeddings", True))},
            norm_type="layernorm",
            parallel_block=True,
            rope_interleaved=True,
            logit_scale=float(hf.get("logit_scale", 0.0625)),
            sliding_window=sw,
            sliding_pattern=pattern,
            nope_pattern=pattern if sw else 0,
        )
    if mt == "olmo2":
        # OLMo-2: NO pre-norms (sublayer outputs are normed), q/k
        # RMSNorm over the full projection width before head reshape
        return LlamaConfig(
            **common, pre_norm=False, post_norms=True, qk_norm_flat=True
        )
    if mt in ("glm", "glm4"):
        # GLM-4: partial rotary (interleaved, first half of head_dim),
        # qkv bias, fused gate_up MLP (split on load); glm4 adds
        # Gemma2-style sandwich norms (post_self_attn/post_mlp)
        return LlamaConfig(
            **common,
            # GLM defaults attention_bias=True but it is a real config
            # knob — honor bias-free checkpoints
            qkv_bias=bool(hf.get("attention_bias", True)),
            rope_interleaved=True,
            partial_rotary=float(hf.get("partial_rotary_factor") or 0.5),
            post_norms=(mt == "glm4"),
        )
    raise ValueError(f"unsupported HF model_type {mt!r}")


def _v2_mscale_fix() -> bool:
    """Opt-in: scale DeepSeek-V2 attention like the released model's
    remote-code modeling (mscale^2 correction) instead of HF's native
    DeepseekV2Attention. See the comment at the use site."""
    import os

    return os.environ.get("DTPU_DEEPSEEK_V2_MSCALE_FIX", "").lower() in (
        "1", "true", "yes"
    )


def _deepseek_config(hf: dict, common: dict, mt: str) -> LlamaConfig:
    """DeepSeek-V2/V3 → LlamaConfig: MLA attention (latent kv, split
    nope/rope head dims, own v dim), dense-prelude + fine-grained MoE
    with shared experts; V3 adds sigmoid scoring with a selection-only
    correction bias and group-limited top-k."""
    if hf.get("attention_bias"):
        raise ValueError(f"{mt} attention_bias=true is not supported")
    v3 = mt == "deepseek_v3"
    mla = dict(
        q_lora_rank=hf.get("q_lora_rank") or 0,
        kv_lora_rank=hf["kv_lora_rank"],
        qk_nope_head_dim=hf["qk_nope_head_dim"],
        qk_rope_head_dim=hf["qk_rope_head_dim"],
        v_head_dim=hf["v_head_dim"],
    )
    rs = hf.get("rope_scaling")
    if rs and rs.get("mscale_all_dim") and (v3 or _v2_mscale_fix()):
        # HF DeepseekV3Attention multiplies the softmax scale by
        # yarn mscale(factor, mscale_all_dim)^2 — and HF's native
        # DeepseekV2Attention does NOT (verified against transformers
        # 4.57.6), while DeepSeek's original remote-code V2 modeling
        # DOES. V2-Lite ships mscale_all_dim=0.707, so the two versions
        # disagree by ~1.59x on the intended attention scale. Default
        # follows HF (so parity tests against HF outputs pass);
        # DTPU_DEEPSEEK_V2_MSCALE_FIX=1 opts V2 into the released
        # model's intended scale (the remote-code behavior). V3 always
        # applies it — both implementations agree there.
        ms = 0.1 * float(rs["mscale_all_dim"]) * math.log(float(rs["factor"])) + 1.0
        qk_dim = hf["qk_nope_head_dim"] + hf["qk_rope_head_dim"]
        mla["attn_scale"] = qk_dim**-0.5 * ms * ms
    n_routed = hf.get("n_routed_experts")
    n_layers = hf["num_hidden_layers"]
    first_k = hf.get("first_k_dense_replace", 0)
    if not n_routed or first_k >= n_layers:
        # every layer dense: a plain MLA transformer
        return LlamaConfig(**common, **mla)
    if hf.get("moe_layer_freq", 1) != 1:
        raise ValueError(f"{mt} moe_layer_freq != 1 is not supported")
    topk_method = hf.get("topk_method") or ("noaux_tc" if v3 else "greedy")
    if topk_method == "group_limited_greedy" or v3:
        groups = (hf["n_group"], hf["topk_group"])
        if groups == (1, 1):
            groups = ()  # one group of everything = no limiting
    elif topk_method == "greedy":
        groups = ()
    else:
        raise ValueError(f"{mt} topk_method {topk_method!r} is not supported")
    shared = hf.get("n_shared_experts") or 0
    moe_inter = hf["moe_intermediate_size"]
    common = {**common, "intermediate_size": moe_inter}
    return LlamaConfig(
        **common,
        **mla,
        n_experts=n_routed,
        experts_per_token=hf["num_experts_per_tok"],
        router_renorm=bool(hf.get("norm_topk_prob", False)),
        router_score="sigmoid" if v3 else "softmax",
        router_bias=v3,  # e_score_correction_bias (noaux_tc)
        router_groups=groups,
        routed_scale=float(hf.get("routed_scaling_factor", 1.0)),
        moe_shared_expert=shared > 0,
        moe_shared_intermediate=shared * moe_inter,
        first_k_dense=first_k,
        dense_intermediate=hf["intermediate_size"],
    )


def _llama4_config(hf: dict, common: dict) -> LlamaConfig:
    """Llama4 text tower → LlamaConfig (interleaved rope, periodic NoPE
    layers, chunked attention, qk L2 norm, temperature tuning,
    sigmoid-input-scaled MoE with a shared expert)."""
    n_layers = hf["num_hidden_layers"]
    # every layer must be MoE: the uniform layer stack can't express
    # Maverick's interleaved dense/MoE layers
    step = hf.get("interleave_moe_layer_step", 1)
    moe_layers = hf.get("moe_layers")
    if step != 1 or (moe_layers is not None and len(moe_layers) != n_layers):
        raise ValueError(
            "llama4 with interleaved dense/MoE layers "
            "(interleave_moe_layer_step != 1) is not supported"
        )
    # no_rope_layers: 1 = rope, 0 = NoPE; expect the periodic
    # every-p-th-layer-NoPE layout
    nrl = hf.get("no_rope_layers")
    if nrl:
        nope_ix = [i for i, use_rope in enumerate(nrl) if not use_rope]
        if not nope_ix:
            pattern = 0
        else:
            pattern = nope_ix[0] + 1
            expect = [0 if (i + 1) % pattern == 0 else 1 for i in range(n_layers)]
            if [1 if r else 0 for r in nrl] != expect:
                raise ValueError(
                    f"llama4 no_rope_layers {nrl!r} is not the periodic "
                    f"1-NoPE-per-{pattern} layout this stack expresses"
                )
    else:
        pattern = 4
    return LlamaConfig(
        **common,
        rope_interleaved=True,
        nope_pattern=pattern,
        attention_chunk_size=hf.get("attention_chunk_size") or 0,
        qk_l2_norm=bool(hf.get("use_qk_norm", True)),
        attn_temp_scale=(
            float(hf.get("attn_scale", 0.1))
            if hf.get("attn_temperature_tuning") else 0.0
        ),
        attn_temp_floor=float(hf.get("floor_scale", 8192.0)),
        n_experts=hf["num_local_experts"],
        experts_per_token=hf.get("num_experts_per_tok", 1),
        router_sigmoid_input=True,
        moe_shared_expert=True,
    )


def _gemma3_pattern(hf: dict, sliding_window: int) -> tuple[int, int]:
    """Gemma3 layer layout → (sliding_window, sliding_pattern).

    Newer HF configs spell the layout as an explicit ``layer_types``
    list; older ones as ``sliding_window_pattern`` (every p-th layer
    global). Only the periodic layouts our stack expresses are
    accepted — an aperiodic list is a hard error, not silent full
    attention. When no layer actually slides, the window is zeroed
    too: (sw, pattern=0) with sw > 0 would mean "uniform sliding" to
    :func:`~dstack_tpu.models.llama.layer_windows`."""
    lt = hf.get("layer_types")
    if lt:
        if not sliding_window or "sliding_attention" not in lt:
            return 0, 0  # all-global layout: no window anywhere
        globals_ix = [i for i, t in enumerate(lt) if t == "full_attention"]
        if not globals_ix:
            return sliding_window, 0  # uniform sliding (n_layers < pattern)
        p = globals_ix[0] + 1
        expect = [
            "full_attention" if (i + 1) % p == 0 else "sliding_attention"
            for i in range(len(lt))
        ]
        if lt != expect:
            raise ValueError(
                f"gemma3 layer_types {lt!r} is not the periodic "
                f"1-global-per-{p} layout this stack expresses"
            )
        return sliding_window, p
    return sliding_window, int(hf.get("sliding_window_pattern") or 6)


# MoE tensor naming per family: (router weight, expert prefix,
# (gate, up, down) per-expert names) — ONE table consumed by both
# convert_state_dict and export_state_dict so import/export round-trip
# symmetry can't drift.
_MOE_NAMES = {
    "qwen3_moe": (
        "mlp.gate.weight", "mlp.experts",
        ("gate_proj", "up_proj", "down_proj"),
    ),
    "mixtral": (
        "block_sparse_moe.gate.weight", "block_sparse_moe.experts",
        ("w1", "w3", "w2"),
    ),
}


def _rope_scaling_from_hf(hf: dict) -> Optional[tuple]:
    """HF ``rope_scaling`` → :class:`LlamaConfig` tuple (llama3 only).

    Llama-3.1/3.2 checkpoints rescale rope frequencies; ignoring the
    field would load without error but generate silently-degraded text,
    so unknown scaling types are a hard error.
    """
    rs = hf.get("rope_scaling")
    if not rs:
        return None
    rope_type = rs.get("rope_type") or rs.get("type")
    if rope_type in (None, "default"):
        return None
    if rope_type == "llama3":
        return (
            float(rs["factor"]),
            float(rs["low_freq_factor"]),
            float(rs["high_freq_factor"]),
            float(rs["original_max_position_embeddings"]),
        )
    if rope_type == "linear":
        # classic position interpolation (Gemma3 global layers):
        # every frequency divided by the factor
        return ("linear", float(rs["factor"]))
    if rope_type == "yarn":
        # NTK-by-parts YaRN (DeepSeek): mirror HF's
        # _compute_yarn_parameters, resolving the cos/sin attention
        # factor from mscale/mscale_all_dim at conversion time
        truncate = bool(rs.get("truncate", True))
        factor = float(rs["factor"])

        def get_mscale(scale, ms=1.0):
            return 1.0 if scale <= 1 else 0.1 * ms * math.log(scale) + 1.0

        att = rs.get("attention_factor")
        if att is None:
            mscale = rs.get("mscale")
            mscale_all = rs.get("mscale_all_dim")
            if mscale and mscale_all:
                att = get_mscale(factor, mscale) / get_mscale(factor, mscale_all)
            else:
                att = get_mscale(factor)
        orig = (
            rs.get("original_max_position_embeddings")
            or hf.get("max_position_embeddings", 8192)
        )
        return (
            "yarn", factor,
            float(rs.get("beta_fast") or 32),
            float(rs.get("beta_slow") or 1),
            float(orig), float(att),
            # canonical form: the truncate element appears ONLY when
            # False (gpt-oss), so truncate-True configs keep the 6-tuple
            # shape existing presets/round-trips use
        ) + ((False,) if not truncate else ())
    raise ValueError(f"unsupported rope_scaling type {rope_type!r}")


def _to_np(t) -> np.ndarray:
    """Torch tensor / numpy / jax array → numpy (bf16 via float32)."""
    if isinstance(t, np.ndarray):
        return t
    if hasattr(t, "detach"):  # torch
        t = t.detach()
        if str(t.dtype) == "torch.bfloat16":
            t = t.float()
        return t.cpu().numpy()
    return np.asarray(t)


def convert_state_dict(
    sd: dict, config: LlamaConfig, model_type: str = "llama"
) -> dict:
    """Flat HF state dict (name → tensor) → our nested params pytree.

    Accepts torch tensors, numpy, or jax arrays as values; returns
    ``config.dtype`` **host (numpy) arrays** with scanned ``[L, ...]``
    layer stacks — staying on host lets the caller ``jax.device_put``
    the tree straight into sharded device buffers (a 70B must never
    materialize on one chip; ml_dtypes provides the numpy bfloat16).
    """
    c = config
    dt = c.dtype
    if model_type in ("deepseek_v2", "deepseek_v3"):
        return _convert_deepseek(sd, c)
    if model_type == "phi3":
        sd = _split_phi3(dict(sd), c)
    if model_type in ("glm", "glm4"):
        sd = _split_glm(dict(sd), c, model_type)
    if model_type == "nemotron":
        sd = _stack_nemotron_norms(dict(sd), c)
    if model_type == "starcoder2":
        sd = dict(sd)
        for i in range(c.n_layers):  # c_fc/c_proj → the unified names
            P = f"model.layers.{i}.mlp."
            for suff in ("weight", "bias"):
                if P + f"c_fc.{suff}" in sd:
                    sd[P + f"up_proj.{suff}"] = sd.pop(P + f"c_fc.{suff}")
                if P + f"c_proj.{suff}" in sd:
                    sd[P + f"down_proj.{suff}"] = sd.pop(P + f"c_proj.{suff}")
        sd = _stack_nemotron_norms(sd, c)  # same stacked-norm layout

    def get(name):
        if name not in sd:
            raise KeyError(
                f"missing weight {name!r} (have e.g. {sorted(sd)[:5]})"
            )
        return _to_np(sd[name])

    def stack(fmt, transpose=False):
        mats = []
        for i in range(c.n_layers):
            m = get(fmt.format(i=i))
            mats.append(m.T if transpose else m)
        return np.asarray(np.stack(mats), dt)

    if model_type in ("gemma3", "llama4"):
        # multimodal checkpoint: keep the text tower, drop the vision
        # weights. Both layouts normalize to model.*:
        #   language_model.model.layers...   (<= 4.51)
        #   model.language_model.layers...   (>= 4.52)
        stripped = {}
        for k, v in sd.items():
            if "language_model." not in k:
                continue  # vision tower / projector
            k = k.replace("model.language_model.", "model.", 1)
            k = k.replace("language_model.", "", 1)
            stripped[k] = v
        sd = stripped or sd
    llama4 = model_type in ("llama4", "llama4_text")

    P = "model.layers.{i}."
    # families whose pre-MLP norm is named pre_feedforward_layernorm
    # (sandwich-norm layouts; _split_glm renames glm4 into this shape)
    gemma2 = model_type in ("gemma2", "gemma3", "gemma3_text", "glm4")
    layers = {
        "wq": stack(P + "self_attn.q_proj.weight", transpose=True),
        "wk": stack(P + "self_attn.k_proj.weight", transpose=True),
        "wv": stack(P + "self_attn.v_proj.weight", transpose=True),
        "wo": stack(P + "self_attn.o_proj.weight", transpose=True),
    }
    if c.pre_norm:
        layers["attn_norm"] = stack(P + "input_layernorm.weight")
        if c.parallel_block:
            pass  # Cohere: attn_norm IS the shared norm (single leaf)
        else:
            # Gemma2's post_attention_layernorm norms the attention
            # *output*; everywhere else it is the pre-MLP norm
            layers["mlp_norm"] = stack(
                P + ("pre_feedforward_layernorm.weight" if gemma2
                     else "post_attention_layernorm.weight")
            )
    if c.qkv_bias:
        layers["bq"] = stack(P + "self_attn.q_proj.bias")
        layers["bk"] = stack(P + "self_attn.k_proj.bias")
        layers["bv"] = stack(P + "self_attn.v_proj.bias")
    if c.proj_bias:  # StarCoder2 / gpt-oss: o (and dense-MLP) biases
        layers["bo"] = stack(P + "self_attn.o_proj.bias")
        if not c.n_experts:
            layers["b_up"] = stack(P + "mlp.up_proj.bias")
            layers["b_down"] = stack(P + "mlp.down_proj.bias")
    if c.attn_sinks:
        layers["sinks"] = np.stack([
            _to_np(get(f"model.layers.{i}.self_attn.sinks")).astype(np.float32)
            for i in range(c.n_layers)
        ])
    if c.qk_norm or c.qk_norm_flat:
        layers["q_norm"] = stack(P + "self_attn.q_norm.weight")
        layers["k_norm"] = stack(P + "self_attn.k_norm.weight")
    if c.post_norms:
        layers["attn_post_norm"] = stack(P + "post_attention_layernorm.weight")
        layers["mlp_post_norm"] = stack(P + "post_feedforward_layernorm.weight")
    if c.n_experts and llama4:
        # Llama4 ships the experts FUSED and PRE-STACKED:
        #   experts.gate_up_proj [E, H, 2F]  (gate then up, no transpose)
        #   experts.down_proj    [E, F, H]
        #   router.weight        [E, H]  (nn.Linear [out, in])
        # plus a dense shared expert with plain Linear layout.
        gus, downs, routers = [], [], []
        for i in range(c.n_layers):
            F = f"model.layers.{i}.feed_forward."
            gus.append(_to_np(get(F + "experts.gate_up_proj")))
            downs.append(_to_np(get(F + "experts.down_proj")))
            routers.append(_to_np(get(F + "router.weight")).T)
        gu = np.stack(gus)  # [L, E, H, 2F]
        layers["w_gate"] = np.asarray(gu[..., : c.intermediate_size], dt)
        layers["w_up"] = np.asarray(gu[..., c.intermediate_size :], dt)
        layers["w_down"] = np.asarray(np.stack(downs), dt)
        layers["w_router"] = np.asarray(np.stack(routers), dt)
        SE = "feed_forward.shared_expert."
        layers["w_shared_gate"] = stack(P + SE + "gate_proj.weight", transpose=True)
        layers["w_shared_up"] = stack(P + SE + "up_proj.weight", transpose=True)
        layers["w_shared_down"] = stack(P + SE + "down_proj.weight", transpose=True)
    elif c.n_experts and model_type == "gpt_oss":
        # gpt-oss ships experts FUSED, PRE-STACKED and INTERLEAVED:
        #   experts.gate_up_proj [E, H, 2F] with gate = [..., ::2],
        #   up = [..., 1::2] (HF GptOssExperts), biases [E, 2F] the
        #   same way; down_proj [E, F, H] + bias [E, H]; router is a
        #   true Linear [E, H] + [E].
        gus, gubs, downs, downbs, routers, rbs = [], [], [], [], [], []
        for i in range(c.n_layers):
            F = f"model.layers.{i}.mlp."
            gus.append(_to_np(get(F + "experts.gate_up_proj")))
            gubs.append(_to_np(get(F + "experts.gate_up_proj_bias")))
            downs.append(_to_np(get(F + "experts.down_proj")))
            downbs.append(_to_np(get(F + "experts.down_proj_bias")))
            routers.append(_to_np(get(F + "router.weight")).T)
            rbs.append(_to_np(get(F + "router.bias")))
        gu = np.stack(gus)  # [L, E, H, 2F]
        gub = np.stack(gubs)  # [L, E, 2F]
        layers["w_gate"] = np.asarray(gu[..., ::2], dt)
        layers["w_up"] = np.asarray(gu[..., 1::2], dt)
        layers["b_gate"] = np.asarray(gub[..., ::2], dt)
        layers["b_up_e"] = np.asarray(gub[..., 1::2], dt)
        layers["w_down"] = np.asarray(np.stack(downs), dt)
        layers["b_down_e"] = np.asarray(np.stack(downbs), dt)
        layers["w_router"] = np.asarray(np.stack(routers), dt)
        layers["b_router"] = np.stack(rbs).astype(np.float32)
    elif c.n_experts:
        router, expert_prefix, (g, u, d) = _MOE_NAMES.get(
            model_type, _MOE_NAMES["mixtral"]
        )
        names = (("w_gate", g), ("w_up", u), ("w_down", d))
        layers["w_router"] = stack(P + router, transpose=True)
        for ours, theirs in names:
            per_layer = []
            for i in range(c.n_layers):
                per_layer.append(
                    np.stack([
                        get(f"model.layers.{i}.{expert_prefix}.{e}.{theirs}.weight").T
                        for e in range(c.n_experts)
                    ])
                )
            layers[ours] = np.asarray(np.stack(per_layer), dt)
    else:
        if not c.mlp_gateless:
            layers["w_gate"] = stack(P + "mlp.gate_proj.weight", transpose=True)
        layers["w_up"] = stack(P + "mlp.up_proj.weight", transpose=True)
        layers["w_down"] = stack(P + "mlp.down_proj.weight", transpose=True)

    params = {
        "embed": np.asarray(get("model.embed_tokens.weight"), dt),
        "layers": layers,
        "final_norm": np.asarray(get("model.norm.weight"), dt),
    }
    if not c.tie_embeddings:
        params["lm_head"] = np.asarray(get("lm_head.weight").T, dt)
    return params


def _convert_deepseek(sd: dict, c: LlamaConfig) -> dict:
    """DeepSeek-V2/V3 state dict → params: MLA projections plus the
    dense-prelude/MoE layer split (``first_k_dense`` layers stack into
    ``dense_layers``, the rest into ``layers``)."""
    dt = c.dtype

    def get(name):
        if name not in sd:
            raise KeyError(
                f"missing weight {name!r} (have e.g. {sorted(sd)[:5]})"
            )
        return _to_np(sd[name])

    def stack(fmt, rows, transpose=False):
        mats = [get(fmt.format(i=i)) for i in rows]
        if transpose:
            mats = [m.T for m in mats]
        return np.asarray(np.stack(mats), dt)

    def attn_and_norms(rows):
        A = "model.layers.{i}.self_attn."
        d = {
            "attn_norm": stack("model.layers.{i}.input_layernorm.weight", rows),
            "mlp_norm": stack(
                "model.layers.{i}.post_attention_layernorm.weight", rows
            ),
            "wkv_a": stack(A + "kv_a_proj_with_mqa.weight", rows, transpose=True),
            "kv_a_norm": stack(A + "kv_a_layernorm.weight", rows),
            "wkv_b": stack(A + "kv_b_proj.weight", rows, transpose=True),
            "wo": stack(A + "o_proj.weight", rows, transpose=True),
        }
        if c.q_lora_rank:
            d["wq_a"] = stack(A + "q_a_proj.weight", rows, transpose=True)
            d["q_a_norm"] = stack(A + "q_a_layernorm.weight", rows)
            d["wq_b"] = stack(A + "q_b_proj.weight", rows, transpose=True)
        else:
            d["wq"] = stack(A + "q_proj.weight", rows, transpose=True)
        return d

    def dense_mlp(rows):
        return {
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight", rows, transpose=True),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight", rows, transpose=True),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight", rows, transpose=True),
        }

    K = c.first_k_dense
    main_rows = list(range(K, c.n_layers))
    layers = attn_and_norms(main_rows)
    if c.n_experts:
        layers["w_router"] = stack(
            "model.layers.{i}.mlp.gate.weight", main_rows, transpose=True
        )
        if c.router_bias:
            layers["router_bias"] = np.asarray(
                np.stack([
                    get(f"model.layers.{i}.mlp.gate.e_score_correction_bias")
                    for i in main_rows
                ]),
                np.float32,  # selection bias stays f32 (HF buffer dtype)
            )
        for ours, theirs in (
            ("w_gate", "gate_proj"), ("w_up", "up_proj"), ("w_down", "down_proj")
        ):
            layers[ours] = np.asarray(
                np.stack([
                    np.stack([
                        get(
                            f"model.layers.{i}.mlp.experts.{e}.{theirs}.weight"
                        ).T
                        for e in range(c.n_experts)
                    ])
                    for i in main_rows
                ]),
                dt,
            )
        if c.moe_shared_expert:
            S = "model.layers.{i}.mlp.shared_experts."
            layers["w_shared_gate"] = stack(S + "gate_proj.weight", main_rows, transpose=True)
            layers["w_shared_up"] = stack(S + "up_proj.weight", main_rows, transpose=True)
            layers["w_shared_down"] = stack(S + "down_proj.weight", main_rows, transpose=True)
    else:
        layers.update(dense_mlp(main_rows))

    params = {
        "embed": np.asarray(get("model.embed_tokens.weight"), dt),
        "layers": layers,
        "final_norm": np.asarray(get("model.norm.weight"), dt),
    }
    if K:
        dense_rows = list(range(K))
        params["dense_layers"] = {
            **attn_and_norms(dense_rows), **dense_mlp(dense_rows)
        }
    if not c.tie_embeddings:
        params["lm_head"] = np.asarray(get("lm_head.weight").T, dt)
    return params


def _stack_nemotron_norms(sd: dict, c: LlamaConfig) -> dict:
    """Nemotron LayerNorm1P carries weight AND bias; our tree stores
    them stacked [2, H] (scale-1 row then bias row — the checkpoint's
    weight already IS scale-1 since forward uses weight + 1)."""
    names = ["model.norm"]
    for i in range(c.n_layers):
        names += [
            f"model.layers.{i}.input_layernorm",
            f"model.layers.{i}.post_attention_layernorm",
        ]
    for n in names:
        w = _to_np(sd.pop(n + ".weight"))
        b = _to_np(sd.pop(n + ".bias"))
        sd[n + ".weight"] = np.stack([w, b])
    return sd


def _split_glm(sd: dict, c: LlamaConfig, model_type: str) -> dict:
    """GLM fuses gate/up into ``gate_up_proj`` ([2F, H] rows: gate then
    up) — split it; glm4's sandwich norms are renamed into the
    Gemma2-style names the generic path reads (post_self_attn →
    post_attention, post_attention → pre_feedforward, post_mlp →
    post_feedforward)."""
    F = c.intermediate_size
    for i in range(c.n_layers):
        P = f"model.layers.{i}."
        gu = _to_np(sd.pop(P + "mlp.gate_up_proj.weight"))
        sd[P + "mlp.gate_proj.weight"] = gu[:F]
        sd[P + "mlp.up_proj.weight"] = gu[F:]
        if model_type == "glm4":
            attn_post = sd.pop(P + "post_self_attn_layernorm.weight")
            pre_mlp = sd.pop(P + "post_attention_layernorm.weight")
            mlp_post = sd.pop(P + "post_mlp_layernorm.weight")
            sd[P + "post_attention_layernorm.weight"] = attn_post
            sd[P + "pre_feedforward_layernorm.weight"] = pre_mlp
            sd[P + "post_feedforward_layernorm.weight"] = mlp_post
    return sd


def _split_phi3(sd: dict, c: LlamaConfig) -> dict:
    """Phi-3 fuses q/k/v into ``qkv_proj`` and gate/up into
    ``gate_up_proj`` ([out, in] rows: q then k then v; gate then up) —
    split them into the standard per-projection names."""
    for i in range(c.n_layers):
        P = f"model.layers.{i}."
        qkv = _to_np(sd.pop(P + "self_attn.qkv_proj.weight"))
        q, k, v = np.split(qkv, [c.q_dim, c.q_dim + c.kv_dim], axis=0)
        sd[P + "self_attn.q_proj.weight"] = q
        sd[P + "self_attn.k_proj.weight"] = k
        sd[P + "self_attn.v_proj.weight"] = v
        gu = _to_np(sd.pop(P + "mlp.gate_up_proj.weight"))
        gate, up = np.split(gu, 2, axis=0)
        sd[P + "mlp.gate_proj.weight"] = gate
        sd[P + "mlp.up_proj.weight"] = up
    return sd


def _load_raw_state_dict(path: Path) -> dict:
    """Read all weight shards in a ``save_pretrained`` directory."""
    safes = sorted(path.glob("*.safetensors"))
    if safes:
        from safetensors import safe_open

        sd = {}
        for f in safes:
            # framework="pt": torch tensors carry bf16 losslessly;
            # _to_np upcasts on conversion
            with safe_open(f, framework="pt") as st:
                for name in st.keys():
                    sd[name] = st.get_tensor(name)
        return sd
    bins = sorted(path.glob("pytorch_model*.bin"))
    if bins:
        import torch

        sd = {}
        for f in bins:
            sd.update(torch.load(f, map_location="cpu", weights_only=True))
        return sd
    raise FileNotFoundError(f"no *.safetensors or pytorch_model*.bin in {path}")


def load_checkpoint(
    path: str, dtype: Any = jnp.bfloat16
) -> tuple[LlamaConfig, dict]:
    """Load an HF ``save_pretrained`` directory → (config, params)."""
    p = Path(path)
    hf = json.loads((p / "config.json").read_text())
    config = config_from_hf(hf, dtype=dtype)
    sd = _load_raw_state_dict(p)
    params = convert_state_dict(sd, config, hf.get("model_type", "llama"))
    return config, params


def config_to_hf(config: LlamaConfig) -> dict:
    """:class:`LlamaConfig` → HF ``config.json`` dict (inverse of
    :func:`config_from_hf` for the families we can express)."""
    c = config
    if c.attn_sinks or c.moe_bias or c.router_topk_softmax:
        # the generic MoE branch would tag this "mixtral" and silently
        # drop sinks/expert biases/router semantics — refuse rather
        # than mis-export (module policy); re-serve gpt-oss fine-tunes
        # through this framework's engine instead
        raise ValueError(
            "gpt-oss configs (attention sinks / biased experts / "
            "topk-softmax router) cannot be exported as an HF "
            "checkpoint yet"
        )
    hf = {
        "hidden_act": (
            "gelu_pytorch_tanh" if c.hidden_act == "gelu_tanh" else "silu"
        ),
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.n_layers,
        "num_attention_heads": c.n_heads,
        "num_key_value_heads": c.n_kv_heads,
        "head_dim": c.head_dim,
        "intermediate_size": c.intermediate_size,
        "rope_theta": c.rope_theta,
        "rms_norm_eps": c.norm_eps,
        "max_position_embeddings": c.max_seq_len,
        "tie_word_embeddings": c.tie_embeddings,
        "torch_dtype": "bfloat16",
    }
    if c.rope_scaling is not None and c.rope_scaling[0] == "linear":
        hf["rope_scaling"] = {
            "rope_type": "linear", "factor": float(c.rope_scaling[1])
        }
    elif c.rope_scaling is not None and c.rope_scaling[0] == "yarn":
        _, factor, beta_fast, beta_slow, orig, att = c.rope_scaling[:6]
        hf["rope_scaling"] = {
            "rope_type": "yarn",
            "factor": factor,
            "beta_fast": beta_fast,
            "beta_slow": beta_slow,
            "original_max_position_embeddings": int(orig),
            "attention_factor": att,  # resolved; HF reads it directly
        }
        if len(c.rope_scaling) > 6:  # gpt-oss: truncate=false round trip
            hf["rope_scaling"]["truncate"] = bool(c.rope_scaling[6])
    elif c.rope_scaling is not None:
        rs = c.rope_scaling
        factor, low_f, high_f, orig = rs[1:] if rs[0] == "llama3" else rs
        hf["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": factor,
            "low_freq_factor": low_f,
            "high_freq_factor": high_f,
            "original_max_position_embeddings": int(orig),
        }
    if c.mla:
        v3 = c.router_score == "sigmoid"
        hf.update(
            model_type="deepseek_v3" if v3 else "deepseek_v2",
            head_dim=c.qk_rope_head_dim,  # HF rope dim for deepseek
            q_lora_rank=c.q_lora_rank or None,
            kv_lora_rank=c.kv_lora_rank,
            qk_nope_head_dim=c.qk_nope_head_dim,
            qk_rope_head_dim=c.qk_rope_head_dim,
            v_head_dim=c.v_head_dim,
        )
        if (
            (v3 or _v2_mscale_fix())
            and c.attn_scale is not None
            and "rope_scaling" in hf
        ):
            # invert the mscale^2 softmax-scale correction back into
            # mscale_all_dim so HF reapplies it (and our loader
            # re-derives attn_scale on the round trip; V2 only when the
            # fix flag is on — mirrors the load-side gate)
            factor = hf["rope_scaling"]["factor"]
            ms = math.sqrt(c.attn_scale * c.qk_head_dim**0.5)
            hf["rope_scaling"]["mscale_all_dim"] = (
                (ms - 1.0) / (0.1 * math.log(factor))
            )
        if c.n_experts:
            shared = (
                c.moe_shared_intermediate // c.intermediate_size
                if c.moe_shared_expert else None
            )
            hf.update(
                n_routed_experts=c.n_experts,
                num_experts_per_tok=c.experts_per_token,
                moe_intermediate_size=c.intermediate_size,
                intermediate_size=c.dense_intermediate or c.intermediate_size,
                first_k_dense_replace=c.first_k_dense,
                moe_layer_freq=1,
                n_shared_experts=shared,
                norm_topk_prob=c.router_renorm,
                routed_scaling_factor=c.routed_scale,
            )
            if v3:
                hf.update(
                    n_group=c.router_groups[0] if c.router_groups else 1,
                    topk_group=c.router_groups[1] if c.router_groups else 1,
                )
            else:
                hf.update(
                    topk_method=(
                        "group_limited_greedy" if c.router_groups else "greedy"
                    ),
                    n_group=c.router_groups[0] if c.router_groups else None,
                    topk_group=c.router_groups[1] if c.router_groups else None,
                )
        else:
            # all-dense MLA: no layer reaches the MoE branch
            hf.update(first_k_dense_replace=c.n_layers, n_routed_experts=None)
        return hf
    if not c.pre_norm:
        hf.update(model_type="olmo2")
        return hf
    if c.embed_multiplier or c.residual_multiplier:
        hf.update(
            model_type="granite",
            embedding_multiplier=c.embed_multiplier or 1.0,
            residual_multiplier=c.residual_multiplier or 1.0,
            # None means the default 1/sqrt(head_dim) — emit the real
            # value so a save/load roundtrip keeps the softmax scale
            attention_multiplier=(
                c.attn_scale if c.attn_scale is not None
                else c.qk_head_dim**-0.5
            ),
            logits_scaling=(1.0 / c.logit_scale) if c.logit_scale else 1.0,
        )
        return hf
    if c.parallel_block:
        if c.sliding_window:
            if c.qk_norm or c.nope_pattern != c.sliding_pattern:
                raise ValueError(
                    "cohere2 export requires nope_pattern == "
                    "sliding_pattern and no qk_norm (the HF config "
                    "cannot express other layouts)"
                )
            hf.update(
                model_type="cohere2",
                layer_norm_eps=c.norm_eps,
                logit_scale=c.logit_scale,
                sliding_window=c.sliding_window,
                sliding_window_pattern=c.sliding_pattern,
                layer_types=[
                    "sliding_attention" if w else "full_attention"
                    for w in _layer_windows(c)
                ],
            )
        else:
            hf.update(
                model_type="cohere",
                layer_norm_eps=c.norm_eps,
                logit_scale=c.logit_scale,
                use_qk_norm=c.qk_norm,
            )
        return hf
    if c.norm_type == "layernorm_bias":
        hf.update(
            model_type="starcoder2",
            norm_epsilon=c.norm_eps,
            use_bias=c.proj_bias,
            sliding_window=c.sliding_window or None,
        )
        return hf
    if c.norm_type == "layernorm1p":
        hf.update(
            model_type="nemotron",
            norm_eps=c.norm_eps,
            partial_rotary_factor=c.partial_rotary,
        )
        hf["hidden_act"] = "relu2"
        return hf
    if c.partial_rotary != 1.0:
        hf.update(
            model_type="glm4" if c.post_norms else "glm",
            attention_bias=c.qkv_bias,
            partial_rotary_factor=c.partial_rotary,
        )
        return hf
    if c.rope_interleaved:
        from dstack_tpu.models.llama import layer_nope as _layer_nope

        hf.update(
            model_type="llama4_text",
            no_rope_layers=[0 if n else 1 for n in _layer_nope(c)],
            attention_chunk_size=c.attention_chunk_size or None,
            use_qk_norm=c.qk_l2_norm,
            attn_temperature_tuning=bool(c.attn_temp_scale),
            attn_scale=c.attn_temp_scale or 0.1,
            floor_scale=c.attn_temp_floor,
            num_local_experts=c.n_experts,
            num_experts_per_tok=c.experts_per_token,
            interleave_moe_layer_step=1,
            intermediate_size_mlp=c.intermediate_size,
        )
    elif c.n_experts and c.qk_norm:
        hf.update(
            model_type="qwen3_moe",
            num_experts=c.n_experts,
            num_experts_per_tok=c.experts_per_token,
            moe_intermediate_size=c.intermediate_size,
            norm_topk_prob=c.router_renorm,
            attention_bias=c.qkv_bias,
        )
    elif c.n_experts:
        hf.update(
            model_type="mixtral",
            num_local_experts=c.n_experts,
            num_experts_per_tok=c.experts_per_token,
        )
    elif c.rope_local_theta:
        hf.update(
            model_type="gemma3_text",
            sliding_window=c.sliding_window or None,
            sliding_window_pattern=c.sliding_pattern or None,
            layer_types=[
                "sliding_attention" if w else "full_attention"
                for w in _layer_windows(c)
            ],
            rope_local_base_freq=c.rope_local_theta,
            query_pre_attn_scalar=(
                round(c.attn_scale**-2) if c.attn_scale else c.head_dim
            ),
        )
    elif c.post_norms:
        hf.update(
            model_type="gemma2",
            sliding_window=c.sliding_window or None,
            attn_logit_softcapping=c.attn_softcap or None,
            final_logit_softcapping=c.logit_softcap or None,
            query_pre_attn_scalar=(
                round(c.attn_scale**-2) if c.attn_scale else c.head_dim
            ),
        )
    elif c.norm_offset:
        hf.update(model_type="gemma")
    elif c.qk_norm:
        hf.update(model_type="qwen3", attention_bias=c.qkv_bias)
    elif c.qkv_bias:
        hf.update(model_type="qwen2")
        if c.sliding_window:
            hf.update(
                use_sliding_window=True,
                sliding_window=c.sliding_window,
                max_window_layers=0,
            )
    elif c.sliding_window:
        hf.update(model_type="mistral", sliding_window=c.sliding_window)
    else:
        hf.update(model_type="llama")
    return hf


def export_state_dict(params: dict, config: LlamaConfig) -> dict:
    """Our params pytree → flat HF state dict (numpy values) — the
    inverse of :func:`convert_state_dict`, so fine-tuned weights serve
    anywhere HF checkpoints do (vLLM, TGI, transformers)."""
    from dstack_tpu.models.quant import is_quantized

    if is_quantized(params):
        raise ValueError("export requires full-precision params, not int8")
    c = config
    mt = config_to_hf(c)["model_type"]
    if mt in ("deepseek_v2", "deepseek_v3"):
        return _export_deepseek(params, c)
    gemma2 = mt in ("gemma2", "gemma3_text", "glm4")

    def np32(x):
        # keep the source dtype (bf16 stays bf16): upcasting every
        # tensor to f32 here would stage a 70B at ~2x its size on host
        return np.asarray(jax.device_get(x))

    sd: dict = {"model.embed_tokens.weight": np32(params["embed"])}
    L = params["layers"]
    for i in range(c.n_layers):
        P = f"model.layers.{i}."
        sd[P + "self_attn.q_proj.weight"] = np32(L["wq"][i]).T
        sd[P + "self_attn.k_proj.weight"] = np32(L["wk"][i]).T
        sd[P + "self_attn.v_proj.weight"] = np32(L["wv"][i]).T
        sd[P + "self_attn.o_proj.weight"] = np32(L["wo"][i]).T
        if c.pre_norm:
            sd[P + "input_layernorm.weight"] = np32(L["attn_norm"][i])
            if not c.parallel_block:  # Cohere's single norm is aliased
                mlp_norm_name = (
                    "pre_feedforward_layernorm.weight" if gemma2
                    else "post_attention_layernorm.weight"
                )
                sd[P + mlp_norm_name] = np32(L["mlp_norm"][i])
        if c.qkv_bias:
            sd[P + "self_attn.q_proj.bias"] = np32(L["bq"][i])
            sd[P + "self_attn.k_proj.bias"] = np32(L["bk"][i])
            sd[P + "self_attn.v_proj.bias"] = np32(L["bv"][i])
        if c.proj_bias:
            sd[P + "self_attn.o_proj.bias"] = np32(L["bo"][i])
            sd[P + "mlp.up_proj.bias"] = np32(L["b_up"][i])
            sd[P + "mlp.down_proj.bias"] = np32(L["b_down"][i])
        if c.qk_norm or c.qk_norm_flat:
            sd[P + "self_attn.q_norm.weight"] = np32(L["q_norm"][i])
            sd[P + "self_attn.k_norm.weight"] = np32(L["k_norm"][i])
        if c.post_norms:
            sd[P + "post_attention_layernorm.weight"] = np32(L["attn_post_norm"][i])
            sd[P + "post_feedforward_layernorm.weight"] = np32(L["mlp_post_norm"][i])
        if c.n_experts and mt == "llama4_text":
            # fused pre-stacked layout (see convert_state_dict)
            F = P + "feed_forward."
            sd[F + "router.weight"] = np32(L["w_router"][i]).T
            sd[F + "experts.gate_up_proj"] = np.concatenate(
                [np32(L["w_gate"][i]), np32(L["w_up"][i])], axis=-1
            )
            sd[F + "experts.down_proj"] = np32(L["w_down"][i])
            SE = F + "shared_expert."
            sd[SE + "gate_proj.weight"] = np32(L["w_shared_gate"][i]).T
            sd[SE + "up_proj.weight"] = np32(L["w_shared_up"][i]).T
            sd[SE + "down_proj.weight"] = np32(L["w_shared_down"][i]).T
        elif c.n_experts:
            router, eprefix, (g, u, d) = _MOE_NAMES.get(
                mt, _MOE_NAMES["mixtral"]
            )
            sd[P + router] = np32(L["w_router"][i]).T
            for e in range(c.n_experts):
                E = P + f"{eprefix}.{e}."
                sd[E + f"{g}.weight"] = np32(L["w_gate"][i][e]).T
                sd[E + f"{u}.weight"] = np32(L["w_up"][i][e]).T
                sd[E + f"{d}.weight"] = np32(L["w_down"][i][e]).T
        else:
            if not c.mlp_gateless:
                sd[P + "mlp.gate_proj.weight"] = np32(L["w_gate"][i]).T
            sd[P + "mlp.up_proj.weight"] = np32(L["w_up"][i]).T
            sd[P + "mlp.down_proj.weight"] = np32(L["w_down"][i]).T
    sd["model.norm.weight"] = np32(params["final_norm"])
    if c.norm_type in ("layernorm1p", "layernorm_bias"):
        # split the stacked (scale, bias) rows back into HF names
        stacked = [n for n in sd if n.endswith("layernorm.weight")]
        for n in stacked + ["model.norm.weight"]:
            wb = sd.pop(n)
            sd[n] = wb[0]
            sd[n[: -len(".weight")] + ".bias"] = wb[1]
    if c.norm_type == "layernorm_bias":
        # back to StarCoder2's c_fc/c_proj MLP names
        for i in range(c.n_layers):
            P = f"model.layers.{i}.mlp."
            for suff in ("weight", "bias"):
                if P + f"up_proj.{suff}" in sd:
                    sd[P + f"c_fc.{suff}"] = sd.pop(P + f"up_proj.{suff}")
                if P + f"down_proj.{suff}" in sd:
                    sd[P + f"c_proj.{suff}"] = sd.pop(P + f"down_proj.{suff}")
    if not c.tie_embeddings:
        sd["lm_head.weight"] = np32(params["lm_head"]).T
    if mt in ("glm", "glm4"):
        # inverse of _split_glm: re-fuse gate/up; restore glm4 norm names
        for i in range(c.n_layers):
            P = f"model.layers.{i}."
            sd[P + "mlp.gate_up_proj.weight"] = np.concatenate(
                [sd.pop(P + "mlp.gate_proj.weight"),
                 sd.pop(P + "mlp.up_proj.weight")],
                axis=0,
            )
            if mt == "glm4":
                attn_post = sd.pop(P + "post_attention_layernorm.weight")
                pre_mlp = sd.pop(P + "pre_feedforward_layernorm.weight")
                mlp_post = sd.pop(P + "post_feedforward_layernorm.weight")
                sd[P + "post_self_attn_layernorm.weight"] = attn_post
                sd[P + "post_attention_layernorm.weight"] = pre_mlp
                sd[P + "post_mlp_layernorm.weight"] = mlp_post
    return sd


def _export_deepseek(params: dict, c: LlamaConfig) -> dict:
    """Inverse of :func:`_convert_deepseek` (flat HF names, numpy)."""

    def np_(x):
        return np.asarray(jax.device_get(x))

    sd: dict = {"model.embed_tokens.weight": np_(params["embed"])}

    def put_layer(sd_row, i, moe):
        P = f"model.layers.{i}."
        A = P + "self_attn."
        sd[P + "input_layernorm.weight"] = np_(sd_row["attn_norm"])
        sd[P + "post_attention_layernorm.weight"] = np_(sd_row["mlp_norm"])
        sd[A + "kv_a_proj_with_mqa.weight"] = np_(sd_row["wkv_a"]).T
        sd[A + "kv_a_layernorm.weight"] = np_(sd_row["kv_a_norm"])
        sd[A + "kv_b_proj.weight"] = np_(sd_row["wkv_b"]).T
        sd[A + "o_proj.weight"] = np_(sd_row["wo"]).T
        if c.q_lora_rank:
            sd[A + "q_a_proj.weight"] = np_(sd_row["wq_a"]).T
            sd[A + "q_a_layernorm.weight"] = np_(sd_row["q_a_norm"])
            sd[A + "q_b_proj.weight"] = np_(sd_row["wq_b"]).T
        else:
            sd[A + "q_proj.weight"] = np_(sd_row["wq"]).T
        if moe:
            sd[P + "mlp.gate.weight"] = np_(sd_row["w_router"]).T
            if c.router_bias:
                sd[P + "mlp.gate.e_score_correction_bias"] = np_(
                    sd_row["router_bias"]
                )
            for e in range(c.n_experts):
                E = P + f"mlp.experts.{e}."
                sd[E + "gate_proj.weight"] = np_(sd_row["w_gate"][e]).T
                sd[E + "up_proj.weight"] = np_(sd_row["w_up"][e]).T
                sd[E + "down_proj.weight"] = np_(sd_row["w_down"][e]).T
            if c.moe_shared_expert:
                S = P + "mlp.shared_experts."
                sd[S + "gate_proj.weight"] = np_(sd_row["w_shared_gate"]).T
                sd[S + "up_proj.weight"] = np_(sd_row["w_shared_up"]).T
                sd[S + "down_proj.weight"] = np_(sd_row["w_shared_down"]).T
        else:
            sd[P + "mlp.gate_proj.weight"] = np_(sd_row["w_gate"]).T
            sd[P + "mlp.up_proj.weight"] = np_(sd_row["w_up"]).T
            sd[P + "mlp.down_proj.weight"] = np_(sd_row["w_down"]).T

    K = c.first_k_dense
    for j in range(K):
        put_layer(
            jax.tree.map(lambda a: a[j], params["dense_layers"]), j, False
        )
    for j in range(c.n_layers - K):
        put_layer(
            jax.tree.map(lambda a: a[j], params["layers"]), K + j,
            bool(c.n_experts),
        )
    sd["model.norm.weight"] = np_(params["final_norm"])
    if not c.tie_embeddings:
        sd["lm_head.weight"] = np_(params["lm_head"]).T
    return sd


def save_checkpoint(config: LlamaConfig, params: dict, path: str) -> None:
    """Write an HF ``save_pretrained``-compatible directory
    (config.json + model.safetensors, bf16).

    The tensors go through torch: safetensors' numpy API mangles
    ml_dtypes bfloat16 arrays (verified: values corrupt on round trip),
    while the torch API stores bf16 natively.
    """
    import ml_dtypes
    import torch
    from safetensors.torch import save_file

    def to_torch_bf16(v: np.ndarray):
        v = np.ascontiguousarray(v)
        if v.dtype == ml_dtypes.bfloat16:
            # bit-exact reinterpretation, no f32 staging
            return torch.from_numpy(v.view(np.uint16)).view(torch.bfloat16)
        return torch.from_numpy(np.asarray(v, np.float32)).to(torch.bfloat16)

    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    (p / "config.json").write_text(json.dumps(config_to_hf(config), indent=2))
    sd = export_state_dict(params, config)
    save_file(
        {k: to_torch_bf16(v) for k, v in sd.items()},
        str(p / "model.safetensors"),
    )
