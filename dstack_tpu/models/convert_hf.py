"""HuggingFace checkpoint → dstack_tpu parameter pytree.

Bridges the serving/fine-tune paths to real released weights: point
``load_checkpoint`` at a ``save_pretrained`` directory (safetensors or
torch ``.bin`` shards) and get back ``(LlamaConfig, params)`` ready for
:func:`dstack_tpu.models.llama.forward`, the serve engine, and the
finetune driver.

Supported ``model_type``s: ``llama``, ``qwen2``, ``qwen3``,
``qwen3_moe``, ``mistral``, ``gemma``, ``gemma2``, ``gemma3``/
``gemma3_text`` (multimodal checkpoints load their text tower),
``mixtral``, ``phi3`` (fused qkv/gate_up projections are split on
load; a Phi-3 export round-trips as the equivalent mistral/llama
layout). Each maps onto :class:`LlamaConfig` family flags (qkv_bias /
sliding_window / norm_offset / softcaps / dual-theta rope / MoE) — the
architecture deltas live in the config, not in per-family model code.

The reference framework never loads weights itself (user containers do);
this module is part of the in-repo inference/training engine that makes
``type: service`` self-contained.

Layout notes:
- HF ``*_proj.weight`` is [out, in] (torch Linear); our kernels want
  [in, out] → transpose.
- HF llama-family checkpoints already use the rotate-half RoPE
  convention (no head permutation needed, unlike Meta's originals).
- Our layer stacks are scanned: every per-layer leaf gains a leading
  ``[n_layers, ...]`` dim.
"""

import json
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dstack_tpu.models.llama import LlamaConfig
from dstack_tpu.models.llama import layer_windows as _layer_windows

__all__ = [
    "config_from_hf",
    "config_to_hf",
    "convert_state_dict",
    "export_state_dict",
    "load_checkpoint",
    "save_checkpoint",
]


def config_from_hf(hf: dict, dtype: Any = jnp.bfloat16) -> LlamaConfig:
    """HF ``config.json`` dict → :class:`LlamaConfig`."""
    mt = hf.get("model_type", "llama")
    if mt in ("gemma3", "llama4") and "text_config" in hf:
        # multimodal wrapper: the text tower's config is nested (the
        # vision tower is out of scope; load_checkpoint strips its
        # weights and the language_model prefix)
        hf = {**hf["text_config"], "model_type": f"{mt}_text"}
        mt = f"{mt}_text"
    hidden = hf["hidden_size"]
    n_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hidden // n_heads
    if hf.get("attention_bias") and mt not in ("qwen2", "qwen3", "qwen3_moe"):
        # q/k/v/o biases exist in the checkpoint but our llama/mistral
        # paths would silently drop them — refuse rather than mis-serve
        raise ValueError(
            f"{mt} checkpoint sets attention_bias=true, which this "
            "converter only supports for qwen2/qwen3"
        )
    act = hf.get("hidden_act") or "silu"
    act_map = {"silu": "silu", "gelu_pytorch_tanh": "gelu_tanh"}
    if mt in ("gemma", "gemma2", "gemma3", "gemma3_text"):
        # Gemma configs historically say "gelu"/hidden_activation but
        # the models always use the tanh approximation
        act = "gelu_tanh"
    elif act not in act_map:
        raise ValueError(
            f"unsupported hidden_act {act!r} (supported: {sorted(act_map)})"
        )
    else:
        act = act_map[act]
    common = dict(
        hidden_act=act,
        vocab_size=hf["vocab_size"],
        hidden_size=hidden,
        n_layers=hf["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=hf.get("num_key_value_heads", n_heads),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        norm_eps=hf.get("rms_norm_eps", 1e-6),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        rope_scaling=_rope_scaling_from_hf(hf),
        dtype=dtype,
    )
    if mt == "llama":
        return LlamaConfig(**common)
    if mt == "qwen2":
        if hf.get("use_sliding_window"):
            # HF Qwen2 windows only layers >= max_window_layers — a
            # layering our periodic sliding_pattern can't express except
            # uniformly; refuse rather than silently run full attention
            if hf.get("max_window_layers", 0) not in (0, None):
                raise ValueError(
                    "qwen2 use_sliding_window with max_window_layers > 0 "
                    "is not supported"
                )
            common["sliding_window"] = hf.get("sliding_window") or 0
        # Qwen2 puts biases on q/k/v only (attention_bias is not in its
        # config; the arch always has them)
        return LlamaConfig(**common, qkv_bias=True)
    if mt == "qwen3":
        lt = hf.get("layer_types") or []
        if hf.get("use_sliding_window") or "sliding_attention" in lt:
            raise ValueError(
                "qwen3 sliding-attention layer_types are not supported"
            )
        return LlamaConfig(
            **common, qk_norm=True,
            qkv_bias=bool(hf.get("attention_bias")),
        )
    if mt == "qwen3_moe":
        # qwen3 attention (qk-norm) + sparse MoE MLP on every layer.
        # Checkpoints mixing dense and sparse layers can't be expressed
        # by the uniform layer stack — refuse rather than mis-run.
        if hf.get("mlp_only_layers") or hf.get("decoder_sparse_step", 1) != 1:
            raise ValueError(
                "qwen3_moe with dense layers (mlp_only_layers / "
                "decoder_sparse_step != 1) is not supported"
            )
        if hf.get("use_sliding_window"):
            raise ValueError("qwen3_moe sliding windows are not supported")
        common["intermediate_size"] = hf["moe_intermediate_size"]
        return LlamaConfig(
            **common,
            qk_norm=True,
            qkv_bias=bool(hf.get("attention_bias")),
            n_experts=hf["num_experts"],
            experts_per_token=hf.get("num_experts_per_tok", 8),
            router_renorm=bool(hf.get("norm_topk_prob", True)),
        )
    if mt == "mistral":
        return LlamaConfig(**common, sliding_window=hf.get("sliding_window") or 0)
    if mt == "phi3":
        if float(hf.get("partial_rotary_factor") or 1.0) != 1.0:
            raise ValueError("phi3 partial_rotary_factor != 1 is not supported")
        return LlamaConfig(**common, sliding_window=hf.get("sliding_window") or 0)
    if mt == "gemma":
        return LlamaConfig(
            **{**common, "tie_embeddings": True},
            norm_offset=True,
            embed_scale=True,
        )
    if mt == "gemma2":
        return LlamaConfig(
            **{**common, "tie_embeddings": True},
            norm_offset=True,
            embed_scale=True,
            post_norms=True,
            sliding_window=hf.get("sliding_window") or 0,
            sliding_pattern=2,  # even layers sliding, odd global
            attn_softcap=hf.get("attn_logit_softcapping") or 0.0,
            logit_softcap=hf.get("final_logit_softcapping") or 0.0,
            attn_scale=float(hf["query_pre_attn_scalar"]) ** -0.5
            if hf.get("query_pre_attn_scalar")
            else None,
        )
    if mt == "mixtral":
        return LlamaConfig(
            **common,
            n_experts=hf["num_local_experts"],
            experts_per_token=hf.get("num_experts_per_tok", 2),
            router_renorm=True,
        )
    if mt in ("gemma3", "gemma3_text"):
        sw = hf.get("sliding_window") or 0
        sw, pattern = _gemma3_pattern(hf, sw)
        return LlamaConfig(
            **{**common, "tie_embeddings": hf.get("tie_word_embeddings", True)},
            norm_offset=True,
            embed_scale=True,
            post_norms=True,
            qk_norm=True,
            sliding_window=sw,
            sliding_pattern=pattern,
            # dual rope: sliding layers rotate at the unscaled local
            # theta, global layers at rope_theta (+ linear scaling)
            rope_local_theta=hf.get("rope_local_base_freq", 10000.0),
            attn_scale=float(hf["query_pre_attn_scalar"]) ** -0.5
            if hf.get("query_pre_attn_scalar")
            else None,
        )
    if mt in ("llama4", "llama4_text"):
        return _llama4_config(hf, common)
    raise ValueError(f"unsupported HF model_type {mt!r}")


def _llama4_config(hf: dict, common: dict) -> LlamaConfig:
    """Llama4 text tower → LlamaConfig (interleaved rope, periodic NoPE
    layers, chunked attention, qk L2 norm, temperature tuning,
    sigmoid-input-scaled MoE with a shared expert)."""
    n_layers = hf["num_hidden_layers"]
    # every layer must be MoE: the uniform layer stack can't express
    # Maverick's interleaved dense/MoE layers
    step = hf.get("interleave_moe_layer_step", 1)
    moe_layers = hf.get("moe_layers")
    if step != 1 or (moe_layers is not None and len(moe_layers) != n_layers):
        raise ValueError(
            "llama4 with interleaved dense/MoE layers "
            "(interleave_moe_layer_step != 1) is not supported"
        )
    # no_rope_layers: 1 = rope, 0 = NoPE; expect the periodic
    # every-p-th-layer-NoPE layout
    nrl = hf.get("no_rope_layers")
    if nrl:
        nope_ix = [i for i, use_rope in enumerate(nrl) if not use_rope]
        if not nope_ix:
            pattern = 0
        else:
            pattern = nope_ix[0] + 1
            expect = [0 if (i + 1) % pattern == 0 else 1 for i in range(n_layers)]
            if [1 if r else 0 for r in nrl] != expect:
                raise ValueError(
                    f"llama4 no_rope_layers {nrl!r} is not the periodic "
                    f"1-NoPE-per-{pattern} layout this stack expresses"
                )
    else:
        pattern = 4
    return LlamaConfig(
        **common,
        rope_interleaved=True,
        nope_pattern=pattern,
        attention_chunk_size=hf.get("attention_chunk_size") or 0,
        qk_l2_norm=bool(hf.get("use_qk_norm", True)),
        attn_temp_scale=(
            float(hf.get("attn_scale", 0.1))
            if hf.get("attn_temperature_tuning") else 0.0
        ),
        attn_temp_floor=float(hf.get("floor_scale", 8192.0)),
        n_experts=hf["num_local_experts"],
        experts_per_token=hf.get("num_experts_per_tok", 1),
        router_sigmoid_input=True,
        moe_shared_expert=True,
    )


def _gemma3_pattern(hf: dict, sliding_window: int) -> tuple[int, int]:
    """Gemma3 layer layout → (sliding_window, sliding_pattern).

    Newer HF configs spell the layout as an explicit ``layer_types``
    list; older ones as ``sliding_window_pattern`` (every p-th layer
    global). Only the periodic layouts our stack expresses are
    accepted — an aperiodic list is a hard error, not silent full
    attention. When no layer actually slides, the window is zeroed
    too: (sw, pattern=0) with sw > 0 would mean "uniform sliding" to
    :func:`~dstack_tpu.models.llama.layer_windows`."""
    lt = hf.get("layer_types")
    if lt:
        if not sliding_window or "sliding_attention" not in lt:
            return 0, 0  # all-global layout: no window anywhere
        globals_ix = [i for i, t in enumerate(lt) if t == "full_attention"]
        if not globals_ix:
            return sliding_window, 0  # uniform sliding (n_layers < pattern)
        p = globals_ix[0] + 1
        expect = [
            "full_attention" if (i + 1) % p == 0 else "sliding_attention"
            for i in range(len(lt))
        ]
        if lt != expect:
            raise ValueError(
                f"gemma3 layer_types {lt!r} is not the periodic "
                f"1-global-per-{p} layout this stack expresses"
            )
        return sliding_window, p
    return sliding_window, int(hf.get("sliding_window_pattern") or 6)


# MoE tensor naming per family: (router weight, expert prefix,
# (gate, up, down) per-expert names) — ONE table consumed by both
# convert_state_dict and export_state_dict so import/export round-trip
# symmetry can't drift.
_MOE_NAMES = {
    "qwen3_moe": (
        "mlp.gate.weight", "mlp.experts",
        ("gate_proj", "up_proj", "down_proj"),
    ),
    "mixtral": (
        "block_sparse_moe.gate.weight", "block_sparse_moe.experts",
        ("w1", "w3", "w2"),
    ),
}


def _rope_scaling_from_hf(hf: dict) -> Optional[tuple]:
    """HF ``rope_scaling`` → :class:`LlamaConfig` tuple (llama3 only).

    Llama-3.1/3.2 checkpoints rescale rope frequencies; ignoring the
    field would load without error but generate silently-degraded text,
    so unknown scaling types are a hard error.
    """
    rs = hf.get("rope_scaling")
    if not rs:
        return None
    rope_type = rs.get("rope_type") or rs.get("type")
    if rope_type in (None, "default"):
        return None
    if rope_type == "llama3":
        return (
            float(rs["factor"]),
            float(rs["low_freq_factor"]),
            float(rs["high_freq_factor"]),
            float(rs["original_max_position_embeddings"]),
        )
    if rope_type == "linear":
        # classic position interpolation (Gemma3 global layers):
        # every frequency divided by the factor
        return ("linear", float(rs["factor"]))
    raise ValueError(f"unsupported rope_scaling type {rope_type!r}")


def _to_np(t) -> np.ndarray:
    """Torch tensor / numpy / jax array → numpy (bf16 via float32)."""
    if isinstance(t, np.ndarray):
        return t
    if hasattr(t, "detach"):  # torch
        t = t.detach()
        if str(t.dtype) == "torch.bfloat16":
            t = t.float()
        return t.cpu().numpy()
    return np.asarray(t)


def convert_state_dict(
    sd: dict, config: LlamaConfig, model_type: str = "llama"
) -> dict:
    """Flat HF state dict (name → tensor) → our nested params pytree.

    Accepts torch tensors, numpy, or jax arrays as values; returns
    ``config.dtype`` **host (numpy) arrays** with scanned ``[L, ...]``
    layer stacks — staying on host lets the caller ``jax.device_put``
    the tree straight into sharded device buffers (a 70B must never
    materialize on one chip; ml_dtypes provides the numpy bfloat16).
    """
    c = config
    dt = c.dtype
    if model_type == "phi3":
        sd = _split_phi3(dict(sd), c)

    def get(name):
        if name not in sd:
            raise KeyError(
                f"missing weight {name!r} (have e.g. {sorted(sd)[:5]})"
            )
        return _to_np(sd[name])

    def stack(fmt, transpose=False):
        mats = []
        for i in range(c.n_layers):
            m = get(fmt.format(i=i))
            mats.append(m.T if transpose else m)
        return np.asarray(np.stack(mats), dt)

    if model_type in ("gemma3", "llama4"):
        # multimodal checkpoint: keep the text tower, drop the vision
        # weights. Both layouts normalize to model.*:
        #   language_model.model.layers...   (<= 4.51)
        #   model.language_model.layers...   (>= 4.52)
        stripped = {}
        for k, v in sd.items():
            if "language_model." not in k:
                continue  # vision tower / projector
            k = k.replace("model.language_model.", "model.", 1)
            k = k.replace("language_model.", "", 1)
            stripped[k] = v
        sd = stripped or sd
    llama4 = model_type in ("llama4", "llama4_text")

    P = "model.layers.{i}."
    gemma2 = model_type in ("gemma2", "gemma3", "gemma3_text")
    layers = {
        "attn_norm": stack(P + "input_layernorm.weight"),
        "wq": stack(P + "self_attn.q_proj.weight", transpose=True),
        "wk": stack(P + "self_attn.k_proj.weight", transpose=True),
        "wv": stack(P + "self_attn.v_proj.weight", transpose=True),
        "wo": stack(P + "self_attn.o_proj.weight", transpose=True),
        # Gemma2's post_attention_layernorm norms the attention *output*;
        # everywhere else it is the pre-MLP norm
        "mlp_norm": stack(
            P + ("pre_feedforward_layernorm.weight" if gemma2
                 else "post_attention_layernorm.weight")
        ),
    }
    if c.qkv_bias:
        layers["bq"] = stack(P + "self_attn.q_proj.bias")
        layers["bk"] = stack(P + "self_attn.k_proj.bias")
        layers["bv"] = stack(P + "self_attn.v_proj.bias")
    if c.qk_norm:
        layers["q_norm"] = stack(P + "self_attn.q_norm.weight")
        layers["k_norm"] = stack(P + "self_attn.k_norm.weight")
    if c.post_norms:
        layers["attn_post_norm"] = stack(P + "post_attention_layernorm.weight")
        layers["mlp_post_norm"] = stack(P + "post_feedforward_layernorm.weight")
    if c.n_experts and llama4:
        # Llama4 ships the experts FUSED and PRE-STACKED:
        #   experts.gate_up_proj [E, H, 2F]  (gate then up, no transpose)
        #   experts.down_proj    [E, F, H]
        #   router.weight        [E, H]  (nn.Linear [out, in])
        # plus a dense shared expert with plain Linear layout.
        gus, downs, routers = [], [], []
        for i in range(c.n_layers):
            F = f"model.layers.{i}.feed_forward."
            gus.append(_to_np(get(F + "experts.gate_up_proj")))
            downs.append(_to_np(get(F + "experts.down_proj")))
            routers.append(_to_np(get(F + "router.weight")).T)
        gu = np.stack(gus)  # [L, E, H, 2F]
        layers["w_gate"] = np.asarray(gu[..., : c.intermediate_size], dt)
        layers["w_up"] = np.asarray(gu[..., c.intermediate_size :], dt)
        layers["w_down"] = np.asarray(np.stack(downs), dt)
        layers["w_router"] = np.asarray(np.stack(routers), dt)
        SE = "feed_forward.shared_expert."
        layers["w_shared_gate"] = stack(P + SE + "gate_proj.weight", transpose=True)
        layers["w_shared_up"] = stack(P + SE + "up_proj.weight", transpose=True)
        layers["w_shared_down"] = stack(P + SE + "down_proj.weight", transpose=True)
    elif c.n_experts:
        router, expert_prefix, (g, u, d) = _MOE_NAMES.get(
            model_type, _MOE_NAMES["mixtral"]
        )
        names = (("w_gate", g), ("w_up", u), ("w_down", d))
        layers["w_router"] = stack(P + router, transpose=True)
        for ours, theirs in names:
            per_layer = []
            for i in range(c.n_layers):
                per_layer.append(
                    np.stack([
                        get(f"model.layers.{i}.{expert_prefix}.{e}.{theirs}.weight").T
                        for e in range(c.n_experts)
                    ])
                )
            layers[ours] = np.asarray(np.stack(per_layer), dt)
    else:
        layers["w_gate"] = stack(P + "mlp.gate_proj.weight", transpose=True)
        layers["w_up"] = stack(P + "mlp.up_proj.weight", transpose=True)
        layers["w_down"] = stack(P + "mlp.down_proj.weight", transpose=True)

    params = {
        "embed": np.asarray(get("model.embed_tokens.weight"), dt),
        "layers": layers,
        "final_norm": np.asarray(get("model.norm.weight"), dt),
    }
    if not c.tie_embeddings:
        params["lm_head"] = np.asarray(get("lm_head.weight").T, dt)
    return params


def _split_phi3(sd: dict, c: LlamaConfig) -> dict:
    """Phi-3 fuses q/k/v into ``qkv_proj`` and gate/up into
    ``gate_up_proj`` ([out, in] rows: q then k then v; gate then up) —
    split them into the standard per-projection names."""
    for i in range(c.n_layers):
        P = f"model.layers.{i}."
        qkv = _to_np(sd.pop(P + "self_attn.qkv_proj.weight"))
        q, k, v = np.split(qkv, [c.q_dim, c.q_dim + c.kv_dim], axis=0)
        sd[P + "self_attn.q_proj.weight"] = q
        sd[P + "self_attn.k_proj.weight"] = k
        sd[P + "self_attn.v_proj.weight"] = v
        gu = _to_np(sd.pop(P + "mlp.gate_up_proj.weight"))
        gate, up = np.split(gu, 2, axis=0)
        sd[P + "mlp.gate_proj.weight"] = gate
        sd[P + "mlp.up_proj.weight"] = up
    return sd


def _load_raw_state_dict(path: Path) -> dict:
    """Read all weight shards in a ``save_pretrained`` directory."""
    safes = sorted(path.glob("*.safetensors"))
    if safes:
        from safetensors import safe_open

        sd = {}
        for f in safes:
            # framework="pt": torch tensors carry bf16 losslessly;
            # _to_np upcasts on conversion
            with safe_open(f, framework="pt") as st:
                for name in st.keys():
                    sd[name] = st.get_tensor(name)
        return sd
    bins = sorted(path.glob("pytorch_model*.bin"))
    if bins:
        import torch

        sd = {}
        for f in bins:
            sd.update(torch.load(f, map_location="cpu", weights_only=True))
        return sd
    raise FileNotFoundError(f"no *.safetensors or pytorch_model*.bin in {path}")


def load_checkpoint(
    path: str, dtype: Any = jnp.bfloat16
) -> tuple[LlamaConfig, dict]:
    """Load an HF ``save_pretrained`` directory → (config, params)."""
    p = Path(path)
    hf = json.loads((p / "config.json").read_text())
    config = config_from_hf(hf, dtype=dtype)
    sd = _load_raw_state_dict(p)
    params = convert_state_dict(sd, config, hf.get("model_type", "llama"))
    return config, params


def config_to_hf(config: LlamaConfig) -> dict:
    """:class:`LlamaConfig` → HF ``config.json`` dict (inverse of
    :func:`config_from_hf` for the families we can express)."""
    c = config
    hf = {
        "hidden_act": (
            "gelu_pytorch_tanh" if c.hidden_act == "gelu_tanh" else "silu"
        ),
        "vocab_size": c.vocab_size,
        "hidden_size": c.hidden_size,
        "num_hidden_layers": c.n_layers,
        "num_attention_heads": c.n_heads,
        "num_key_value_heads": c.n_kv_heads,
        "head_dim": c.head_dim,
        "intermediate_size": c.intermediate_size,
        "rope_theta": c.rope_theta,
        "rms_norm_eps": c.norm_eps,
        "max_position_embeddings": c.max_seq_len,
        "tie_word_embeddings": c.tie_embeddings,
        "torch_dtype": "bfloat16",
    }
    if c.rope_scaling is not None and c.rope_scaling[0] == "linear":
        hf["rope_scaling"] = {
            "rope_type": "linear", "factor": float(c.rope_scaling[1])
        }
    elif c.rope_scaling is not None:
        rs = c.rope_scaling
        factor, low_f, high_f, orig = rs[1:] if rs[0] == "llama3" else rs
        hf["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": factor,
            "low_freq_factor": low_f,
            "high_freq_factor": high_f,
            "original_max_position_embeddings": int(orig),
        }
    if c.rope_interleaved:
        from dstack_tpu.models.llama import layer_nope as _layer_nope

        hf.update(
            model_type="llama4_text",
            no_rope_layers=[0 if n else 1 for n in _layer_nope(c)],
            attention_chunk_size=c.attention_chunk_size or None,
            use_qk_norm=c.qk_l2_norm,
            attn_temperature_tuning=bool(c.attn_temp_scale),
            attn_scale=c.attn_temp_scale or 0.1,
            floor_scale=c.attn_temp_floor,
            num_local_experts=c.n_experts,
            num_experts_per_tok=c.experts_per_token,
            interleave_moe_layer_step=1,
            intermediate_size_mlp=c.intermediate_size,
        )
    elif c.n_experts and c.qk_norm:
        hf.update(
            model_type="qwen3_moe",
            num_experts=c.n_experts,
            num_experts_per_tok=c.experts_per_token,
            moe_intermediate_size=c.intermediate_size,
            norm_topk_prob=c.router_renorm,
            attention_bias=c.qkv_bias,
        )
    elif c.n_experts:
        hf.update(
            model_type="mixtral",
            num_local_experts=c.n_experts,
            num_experts_per_tok=c.experts_per_token,
        )
    elif c.rope_local_theta:
        hf.update(
            model_type="gemma3_text",
            sliding_window=c.sliding_window or None,
            sliding_window_pattern=c.sliding_pattern or None,
            layer_types=[
                "sliding_attention" if w else "full_attention"
                for w in _layer_windows(c)
            ],
            rope_local_base_freq=c.rope_local_theta,
            query_pre_attn_scalar=(
                round(c.attn_scale**-2) if c.attn_scale else c.head_dim
            ),
        )
    elif c.post_norms:
        hf.update(
            model_type="gemma2",
            sliding_window=c.sliding_window or None,
            attn_logit_softcapping=c.attn_softcap or None,
            final_logit_softcapping=c.logit_softcap or None,
            query_pre_attn_scalar=(
                round(c.attn_scale**-2) if c.attn_scale else c.head_dim
            ),
        )
    elif c.norm_offset:
        hf.update(model_type="gemma")
    elif c.qk_norm:
        hf.update(model_type="qwen3", attention_bias=c.qkv_bias)
    elif c.qkv_bias:
        hf.update(model_type="qwen2")
        if c.sliding_window:
            hf.update(
                use_sliding_window=True,
                sliding_window=c.sliding_window,
                max_window_layers=0,
            )
    elif c.sliding_window:
        hf.update(model_type="mistral", sliding_window=c.sliding_window)
    else:
        hf.update(model_type="llama")
    return hf


def export_state_dict(params: dict, config: LlamaConfig) -> dict:
    """Our params pytree → flat HF state dict (numpy values) — the
    inverse of :func:`convert_state_dict`, so fine-tuned weights serve
    anywhere HF checkpoints do (vLLM, TGI, transformers)."""
    from dstack_tpu.models.quant import is_quantized

    if is_quantized(params):
        raise ValueError("export requires full-precision params, not int8")
    c = config
    mt = config_to_hf(c)["model_type"]
    gemma2 = mt in ("gemma2", "gemma3_text")

    def np32(x):
        # keep the source dtype (bf16 stays bf16): upcasting every
        # tensor to f32 here would stage a 70B at ~2x its size on host
        return np.asarray(jax.device_get(x))

    sd: dict = {"model.embed_tokens.weight": np32(params["embed"])}
    L = params["layers"]
    for i in range(c.n_layers):
        P = f"model.layers.{i}."
        sd[P + "input_layernorm.weight"] = np32(L["attn_norm"][i])
        sd[P + "self_attn.q_proj.weight"] = np32(L["wq"][i]).T
        sd[P + "self_attn.k_proj.weight"] = np32(L["wk"][i]).T
        sd[P + "self_attn.v_proj.weight"] = np32(L["wv"][i]).T
        sd[P + "self_attn.o_proj.weight"] = np32(L["wo"][i]).T
        mlp_norm_name = (
            "pre_feedforward_layernorm.weight" if gemma2
            else "post_attention_layernorm.weight"
        )
        sd[P + mlp_norm_name] = np32(L["mlp_norm"][i])
        if c.qkv_bias:
            sd[P + "self_attn.q_proj.bias"] = np32(L["bq"][i])
            sd[P + "self_attn.k_proj.bias"] = np32(L["bk"][i])
            sd[P + "self_attn.v_proj.bias"] = np32(L["bv"][i])
        if c.qk_norm:
            sd[P + "self_attn.q_norm.weight"] = np32(L["q_norm"][i])
            sd[P + "self_attn.k_norm.weight"] = np32(L["k_norm"][i])
        if c.post_norms:
            sd[P + "post_attention_layernorm.weight"] = np32(L["attn_post_norm"][i])
            sd[P + "post_feedforward_layernorm.weight"] = np32(L["mlp_post_norm"][i])
        if c.n_experts and mt == "llama4_text":
            # fused pre-stacked layout (see convert_state_dict)
            F = P + "feed_forward."
            sd[F + "router.weight"] = np32(L["w_router"][i]).T
            sd[F + "experts.gate_up_proj"] = np.concatenate(
                [np32(L["w_gate"][i]), np32(L["w_up"][i])], axis=-1
            )
            sd[F + "experts.down_proj"] = np32(L["w_down"][i])
            SE = F + "shared_expert."
            sd[SE + "gate_proj.weight"] = np32(L["w_shared_gate"][i]).T
            sd[SE + "up_proj.weight"] = np32(L["w_shared_up"][i]).T
            sd[SE + "down_proj.weight"] = np32(L["w_shared_down"][i]).T
        elif c.n_experts:
            router, eprefix, (g, u, d) = _MOE_NAMES.get(
                mt, _MOE_NAMES["mixtral"]
            )
            sd[P + router] = np32(L["w_router"][i]).T
            for e in range(c.n_experts):
                E = P + f"{eprefix}.{e}."
                sd[E + f"{g}.weight"] = np32(L["w_gate"][i][e]).T
                sd[E + f"{u}.weight"] = np32(L["w_up"][i][e]).T
                sd[E + f"{d}.weight"] = np32(L["w_down"][i][e]).T
        else:
            sd[P + "mlp.gate_proj.weight"] = np32(L["w_gate"][i]).T
            sd[P + "mlp.up_proj.weight"] = np32(L["w_up"][i]).T
            sd[P + "mlp.down_proj.weight"] = np32(L["w_down"][i]).T
    sd["model.norm.weight"] = np32(params["final_norm"])
    if not c.tie_embeddings:
        sd["lm_head.weight"] = np32(params["lm_head"]).T
    return sd


def save_checkpoint(config: LlamaConfig, params: dict, path: str) -> None:
    """Write an HF ``save_pretrained``-compatible directory
    (config.json + model.safetensors, bf16).

    The tensors go through torch: safetensors' numpy API mangles
    ml_dtypes bfloat16 arrays (verified: values corrupt on round trip),
    while the torch API stores bf16 natively.
    """
    import ml_dtypes
    import torch
    from safetensors.torch import save_file

    def to_torch_bf16(v: np.ndarray):
        v = np.ascontiguousarray(v)
        if v.dtype == ml_dtypes.bfloat16:
            # bit-exact reinterpretation, no f32 staging
            return torch.from_numpy(v.view(np.uint16)).view(torch.bfloat16)
        return torch.from_numpy(np.asarray(v, np.float32)).to(torch.bfloat16)

    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    (p / "config.json").write_text(json.dumps(config_to_hf(config), indent=2))
    sd = export_state_dict(params, config)
    save_file(
        {k: to_torch_bf16(v) for k, v in sd.items()},
        str(p / "model.safetensors"),
    )
