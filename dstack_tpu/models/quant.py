"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound: the chip reads every weight once per
token while the MXU idles. Storing the projection matrices (attention,
dense/MoE/shared-expert FFNs — see LAYER_TARGETS — and the LM head) as
int8 with per-output-channel scales halves the bytes per step — the dequantize is a cast the MXU input pipeline absorbs plus
one per-channel multiply that XLA fuses into the matmul's epilogue.

Per-output-channel absmax scaling is exact under the contraction: for
W[:, o] quantized as q[:, o]·s[o], x·W ≈ (x·q)·s column-wise, so the
scale multiplies the OUTPUT — no input statistics, no calibration data.

``quantize_tree`` rewrites a params pytree: every target leaf ``name``
becomes ``name_q`` (int8, same shape) + ``name_s`` (f32 scale per
output channel); :func:`dstack_tpu.models.llama._proj` consumes either
form, so training-free quantized serving works through every existing
path (forward, prefill, decode, LoRA bypass on a quantized base).

Norms, biases, and the embedding table stay in model dtype: they are a
rounding error of the byte budget, and the embedding is a gather (no
matmul to fuse a dequant into). MoE expert stacks ([L, E, in, out])
quantize through the same rank-generic absmax — per (expert, output
channel) scales — and models/moe.py resolves the ``_q``/``_s`` form in
its batched expert einsums; routers stay full precision (tiny, and
routing decisions are precision-sensitive). MLA models quantize their
expert/FFN stacks and ``wo`` — nearly all of a DeepSeek checkpoint's
bytes — while the latent attention projections stay full precision
(raw-einsum/absorbed-reshape consumers; see :func:`quant_targets`).
"""

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from dstack_tpu.models.llama import LlamaConfig

# projection leaves quantized inside each layer ([L, in, out] stacks;
# the MoE expert stacks [L, E, in, out] and the fused shared experts
# ride the same rank-generic per-output-channel quantization)
LAYER_TARGETS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "w_shared_gate", "w_shared_up", "w_shared_down",
)


def quantize_weight(w) -> tuple[np.ndarray, np.ndarray]:
    """[..., in, out] → (int8 [..., in, out], f32 scale [..., out]).

    Per-output-channel absmax: q = round(w / s), s = absmax_in / 127.
    Runs on HOST (numpy): serving paths hand the engine a host tree so
    big models go straight into sharded device buffers — quantizing
    eagerly on device would commit every full-precision stack to chip 0
    first, the exact OOM the host-tree contract avoids.
    """
    w32 = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w32), axis=-2)  # [..., out]
    s = np.where(absmax == 0.0, 1.0, absmax / 127.0).astype(np.float32)
    q = np.clip(np.round(w32 / s[..., None, :]), -127, 127).astype(np.int8)
    return q, s


def dequantize_weight(q, s, dtype: Any) -> jax.Array:
    return (jnp.asarray(q, jnp.float32) * jnp.asarray(s)[..., None, :]).astype(dtype)


def quant_targets(config: LlamaConfig) -> tuple:
    """The projection leaves int8 covers for this config.

    MLA models (DeepSeek) keep their latent attention projections
    (``wq_a/wq_b/wkv_a/wkv_b``) in full precision: they are consumed by
    raw einsums and the absorbed-form reshape, and the latent path is
    already the compression — while the expert/FFN stacks and ``wo``
    (a ``_proj`` consumer) carry nearly all of a DeepSeek checkpoint's
    bytes and quantize exactly like any other family's."""
    if config.mla:
        # derived, not hardcoded: a future FFN target added to
        # LAYER_TARGETS must not silently serve full-precision on MLA
        return tuple(
            t for t in LAYER_TARGETS if t not in ("wq", "wk", "wv")
        )
    return LAYER_TARGETS


def _quantize_stack(stack: dict, targets: tuple) -> dict:
    out = {}
    for name, leaf in stack.items():
        if name in targets:
            q, s = quantize_weight(leaf)  # asarray(f32) happens inside
            out[name + "_q"] = q
            out[name + "_s"] = s
        else:
            out[name] = leaf
    return out


def quantize_tree(params: dict, config: LlamaConfig) -> dict:
    """Params pytree → serving pytree with int8 projection weights.

    Quantizes the per-layer projections and the LM head (when untied);
    embedding, norms, biases, and LoRA adapters pass through. The
    DeepSeek dense prelude (``dense_layers``) quantizes its FFN like
    the main stack; see :func:`quant_targets` for the MLA carve-out.
    """
    targets = quant_targets(config)
    out = {
        k: v for k, v in params.items()
        if k not in ("layers", "dense_layers", "lm_head")
    }
    out["layers"] = _quantize_stack(params["layers"], targets)
    if "dense_layers" in params:
        out["dense_layers"] = _quantize_stack(
            params["dense_layers"], targets
        )
    if "lm_head" in params:
        q, s = quantize_weight(params["lm_head"])
        out["lm_head_q"] = q
        out["lm_head_s"] = s
    return out


def quant_param_specs(specs: dict, config: LlamaConfig = None) -> dict:
    """Logical-axis spec tree for a quantized params tree.

    ``name_q`` shards exactly like ``name``; ``name_s`` keeps only the
    output-channel axis (the last spec entry), so tensor-parallel
    serving shards scales alongside their columns. ``config`` picks the
    per-config target set (MLA quantizes FFN + ``wo`` only) — omitted,
    the full LAYER_TARGETS set is assumed (pre-MLA callers).
    """
    targets = quant_targets(config) if config is not None else LAYER_TARGETS

    def spec_stack(stack: dict) -> dict:
        out = {}
        for name, spec in stack.items():
            if name in targets:
                out[name + "_q"] = spec
                # drop the input-dim axis: ("layers", in, out) → ("layers", out)
                out[name + "_s"] = spec[:-2] + spec[-1:]
            else:
                out[name] = spec
        return out

    out = {
        k: v for k, v in specs.items()
        if k not in ("layers", "dense_layers", "lm_head")
    }
    out["layers"] = spec_stack(specs["layers"])
    if "dense_layers" in specs:
        out["dense_layers"] = spec_stack(specs["dense_layers"])
    if "lm_head" in specs:
        out["lm_head_q"] = specs["lm_head"]
        out["lm_head_s"] = specs["lm_head"][-1:]
    return out


def is_quantized(params: dict) -> bool:
    return any(k.endswith("_q") for k in params.get("layers", {}))


def random_quantized_params(config: LlamaConfig, seed: int = 0) -> dict:
    """Benchmark-only: the int8 serving tree with random values, built
    directly in numpy.

    ``init_params`` → ``quantize_tree`` materializes the full-precision
    tree through JAX's host PRNG first — tens of minutes of threefry on
    a small driver VM for an 8B model, which blew the 8B serving
    capture's whole tunnel-window budget. Decode throughput/latency are
    weight-value-independent, so the bench path emits random int8
    projections (+ jittered per-channel scales, so no two channels
    dequantize identically) and random-normal bf16 for everything
    else. Leaf shapes come from ``jax.eval_shape`` over the real
    ``init_params``; the quantized layout (targets, ``_q``/``_s``
    naming, scale shapes) mirrors :func:`quantize_tree` by hand — the
    structure-parity test in ``tests/compute/test_quant.py`` is what
    actually pins the two together."""
    shapes = _random_tree_shapes(config, seed)
    rng = np.random.default_rng(seed)

    def dense(leaf) -> np.ndarray:
        dt = np.dtype(leaf.dtype)
        # standard_normal only emits float32/64; cast after
        return (
            rng.standard_normal(leaf.shape, np.float32) * 0.02
        ).astype(dt)

    def q_and_s(leaf) -> tuple[np.ndarray, np.ndarray]:
        q = rng.integers(
            -127, 128, size=leaf.shape, dtype=np.int8
        )
        s_shape = leaf.shape[:-2] + leaf.shape[-1:]
        s = (
            rng.uniform(0.8, 1.2, s_shape) * (0.02 / 127.0)
        ).astype(np.float32)
        return q, s

    return _assemble_random_tree(shapes, dense, q_and_s)


def _random_tree_shapes(config: LlamaConfig, seed: int) -> dict:
    """Shared prologue for the random-tree generators: the
    unsupported-config guards and the ``eval_shape`` over the real
    ``init_params`` — one copy, so a new precondition cannot drift
    between the host and on-device paths."""
    from functools import partial

    from dstack_tpu.models import llama

    if config.mla:
        raise ValueError(
            "the bench's random int8 tree generator does not cover MLA "
            "configs (real checkpoints DO quantize via quantize_tree; "
            "the bench targets the llama family)"
        )
    shapes = jax.eval_shape(
        partial(llama.init_params, config), jax.random.key(seed)
    )
    if "dense_layers" in shapes:
        raise ValueError(
            "the bench's random int8 tree generator does not cover "
            "dense-prelude stacks (real checkpoints DO quantize via "
            "quantize_tree)"
        )
    return shapes


def _assemble_random_tree(shapes: dict, dense, q_and_s) -> dict:
    """Walk ``init_params``' shape tree into the quantized layout,
    generating each leaf through the supplied callbacks (numpy host
    path or jitted device path — same structure either way, which is
    what the parity test in tests/compute/test_quant.py pins)."""
    out: dict = {}
    for key, leaf in shapes.items():
        if key == "layers":
            layers: dict = {}
            for name, lf in leaf.items():
                if name in LAYER_TARGETS:
                    layers[name + "_q"], layers[name + "_s"] = q_and_s(lf)
                else:
                    layers[name] = dense(lf)
            out["layers"] = layers
        elif key == "lm_head":
            out["lm_head_q"], out["lm_head_s"] = q_and_s(leaf)
        else:
            # embedding / norms / nested aux trees pass through dense
            out[key] = jax.tree_util.tree_map(dense, leaf)
    return out


def random_quantized_params_on_device(
    config: LlamaConfig, seed: int = 0
) -> dict:
    """Benchmark-only: :func:`random_quantized_params`, but every leaf
    is generated ON the accelerator by a small jitted PRNG program.

    Through a tunneled driver host the numpy tree's ``device_put`` is
    the killer — ~8 GB of int8 weights streamed host→device blew the
    8B serving capture twice (timeout, then UNAVAILABLE mid-transfer).
    Here only compiled programs and 16-byte keys cross the link; the
    threefry runs at chip speed. Same tree structure and value
    distributions as the numpy path."""
    from functools import partial

    shapes = _random_tree_shapes(config, seed)
    root = jax.random.key(seed)
    leaf_no = iter(range(1 << 30))

    @partial(jax.jit, static_argnums=(1, 2))
    def _dense(k, shape, dtype):
        return (
            jax.random.normal(k, shape, jnp.float32) * 0.02
        ).astype(dtype)

    @partial(jax.jit, static_argnums=(1,))
    def _q(k, shape):
        return jax.random.randint(k, shape, -127, 128, dtype=jnp.int8)

    @partial(jax.jit, static_argnums=(1,))
    def _s(k, shape):
        return jax.random.uniform(
            k, shape, jnp.float32, 0.8, 1.2
        ) * (0.02 / 127.0)

    def _key():
        return jax.random.fold_in(root, next(leaf_no))

    def dense(leaf):
        return _dense(_key(), tuple(leaf.shape), np.dtype(leaf.dtype))

    def q_and_s(leaf):
        s_shape = tuple(leaf.shape[:-2] + leaf.shape[-1:])
        return (
            _q(_key(), tuple(leaf.shape)),
            _s(_key(), s_shape),
        )

    return _assemble_random_tree(shapes, dense, q_and_s)
