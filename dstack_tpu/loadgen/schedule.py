"""Compile (spec, seed) → a replayable open-loop event schedule.

The compiler is a **pure function**: every draw comes from a named
``random.Random`` stream keyed ``"{seed}:{component}"`` (the
``DTPU_FAULT_PLAN`` determinism idiom — inserting a class or session
never perturbs its neighbors' streams), so the same (spec, seed) always
yields a byte-identical schedule and two soak runs replay the exact
same traffic. The schedule is *open-loop*: event times are fixed at
compile time and the driver fires them regardless of completions —
arrivals never slow down because the system under test is struggling,
which is precisely the queueing behavior closed-loop benches hide
(Schroeder et al., "Open Versus Closed").

Construction, per class:

- Session/request start times come from a Poisson process at the
  class's share of the spec rate (chat classes admit *sessions* at
  ``share × rate / turns`` so their turn stream lands near the share).
  The ``diurnal`` process thins a peak-rate stream against
  ``rate(t) = rate × (1 + amplitude · sin(2πt / period))`` with seeded
  acceptance draws — still a pure function of the seed.
- A chat session's turns follow at seeded exponential think-time gaps;
  turn *k+1*'s message list extends turn *k*'s with a **scripted**
  assistant reply plus the next seeded user message, so prefix chains
  (``routing.affinity.chain_digests``) and the engine's KV prefix
  cache see a real conversation replay while the schedule stays
  completion-independent.
- Completion events carry one seeded prompt string.

Import-light (stdlib + textgen): compiling and diffing schedules needs
neither jax nor aiohttp.
"""

import hashlib
import json
import math
import random
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from dstack_tpu.loadgen.spec import ArrivalSpec, TenantClass, WorkloadSpec
from dstack_tpu.loadgen.textgen import WordRNG, chars_in, session_text


@dataclass(frozen=True)
class Event:
    """One scheduled request. ``t`` is seconds from soak start;
    ``messages`` (chat) or ``prompt`` (completion) is the full request
    content — the driver adds nothing but transport."""

    t: float
    rid: str
    cls: str
    kind: str  # "chat" | "completion"
    tenant: str
    priority: str
    session: Optional[str]  # chat only
    turn: int  # 0-based turn index (0 for completions)
    messages: Optional[Tuple[dict, ...]]  # chat request history
    prompt: Optional[str]  # completion prompt
    max_tokens: int
    stream: bool
    temperature: float
    seed: Optional[int]  # per-request sampling seed (seeded classes)
    ttft_slo_ms: float
    tpot_slo_ms: float

    def to_dict(self) -> dict:
        return {
            "t": round(self.t, 6),
            "rid": self.rid,
            "cls": self.cls,
            "kind": self.kind,
            "tenant": self.tenant,
            "priority": self.priority,
            "session": self.session,
            "turn": self.turn,
            "messages": list(self.messages) if self.messages else None,
            "prompt": self.prompt,
            "max_tokens": self.max_tokens,
            "stream": self.stream,
            "temperature": self.temperature,
            "seed": self.seed,
        }


@dataclass(frozen=True)
class EventSchedule:
    """The compiled schedule plus its identity: ``digest`` is the
    sha256 of the canonical JSONL rendering, so "same workload" is a
    string comparison in a soak artifact."""

    spec: WorkloadSpec
    seed: int
    events: Tuple[Event, ...]

    def to_jsonl(self) -> str:
        return "".join(
            json.dumps(e.to_dict(), sort_keys=True) + "\n"
            for e in self.events
        )

    def digest(self) -> str:
        return hashlib.sha256(self.to_jsonl().encode()).hexdigest()

    def classes(self) -> dict:
        return {c.name: c for c in self.spec.classes}


def _poisson_starts(
    rng: random.Random, arrival: ArrivalSpec, rate: float, duration: float
) -> Iterator[float]:
    """Arrival times on [0, duration) at mean ``rate``; the diurnal
    process thins a peak-rate homogeneous stream (one acceptance draw
    per candidate, always consumed, so the schedule stays a pure
    function of the stream)."""
    if rate <= 0:
        return
    diurnal = arrival.process == "diurnal"
    amp = min(max(arrival.amplitude, 0.0), 1.0) if diurnal else 0.0
    peak = rate * (1.0 + amp)
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration:
            return
        if diurnal:
            inst = rate * (
                1.0 + amp * math.sin(2.0 * math.pi * t / arrival.period_s)
            )
            accept = rng.random() < inst / peak
            if not accept:
                continue
        yield t


def _chat_session_events(
    spec: WorkloadSpec,
    cls: TenantClass,
    seed: int,
    session_ix: int,
    start: float,
) -> List[dict]:
    """All turn events of one session (dicts pre-rid; times past the
    soak end are dropped — the session is truncated, like a user whose
    chat outlives the observation window)."""
    srng = random.Random(f"{seed}:session:{cls.name}:{session_ix}")
    text = WordRNG(random.Random(f"{seed}:text:{cls.name}:{session_ix}"))
    tenant = f"{cls.name}-t{srng.randrange(cls.tenants)}"
    session_id = f"{cls.name}-s{session_ix}"
    out: List[dict] = []
    t = start
    messages: List[dict] = []
    for turn in range(cls.turns):
        if turn > 0:
            t += srng.expovariate(1.0 / max(cls.think_time_s, 1e-6))
            if t >= spec.duration_s:
                break
        messages = list(messages)  # each event owns its prefix snapshot
        messages.append({
            "role": "user",
            "content": session_text(text, chars_in(text, cls.turn_chars)),
        })
        out.append({
            "t": t,
            "cls": cls,
            "tenant": tenant,
            "session": session_id,
            "turn": turn,
            "messages": tuple(messages),
            "prompt": None,
            "max_tokens": _tokens_in(srng, cls.max_tokens),
            "seed": _request_seed(srng, cls),
        })
        # scripted assistant reply: the NEXT turn's history extends this
        # turn's prompt with seeded text, so the prefix chain grows like
        # a live conversation without coupling turn k+1 to turn k's
        # actual completion (open-loop: it may not even have started)
        messages.append({
            "role": "assistant",
            "content": session_text(text, 4 * cls.max_tokens[1]),
        })
    return out


def _tokens_in(rng: random.Random, bounds: Tuple[int, int]) -> int:
    lo, hi = bounds
    return lo if hi <= lo else rng.randint(lo, hi)


def _request_seed(rng: random.Random, cls: TenantClass) -> Optional[int]:
    # ALWAYS advance the stream so toggling `seeded` never shifts the
    # session's later draws (the fault-plan independence idiom)
    s = rng.randrange(1, 2**31)
    return s if cls.seeded else None


def _completion_events(
    spec: WorkloadSpec, cls: TenantClass, seed: int, ix: int, start: float
) -> List[dict]:
    srng = random.Random(f"{seed}:session:{cls.name}:{ix}")
    text = WordRNG(random.Random(f"{seed}:text:{cls.name}:{ix}"))
    tenant = f"{cls.name}-t{srng.randrange(cls.tenants)}"
    return [{
        "t": start,
        "cls": cls,
        "tenant": tenant,
        "session": None,
        "turn": 0,
        "messages": None,
        "prompt": session_text(text, chars_in(text, cls.prompt_chars)),
        "max_tokens": _tokens_in(srng, cls.max_tokens),
        "seed": _request_seed(srng, cls),
    }]


def compile_schedule(spec: WorkloadSpec, seed: int) -> EventSchedule:
    """(spec, seed) → :class:`EventSchedule`. Same inputs, same bytes."""
    if not spec.classes:
        raise ValueError("workload spec has no classes")
    total_share = sum(c.share for c in spec.classes)
    raw: List[dict] = []
    for cls in spec.classes:
        req_rate = spec.arrival.rate_rps * cls.share / total_share
        start_rate = (
            req_rate / cls.turns if cls.kind == "chat" else req_rate
        )
        arng = random.Random(f"{seed}:arrivals:{cls.name}")
        for ix, start in enumerate(
            _poisson_starts(arng, spec.arrival, start_rate, spec.duration_s)
        ):
            if cls.kind == "chat":
                raw.extend(
                    _chat_session_events(spec, cls, seed, ix, start)
                )
            else:
                raw.extend(
                    _completion_events(spec, cls, seed, ix, start)
                )
    # deterministic order: time, then a stable identity tie-break
    raw.sort(
        key=lambda e: (e["t"], e["cls"].name, e["session"] or "", e["turn"])
    )
    events = tuple(
        Event(
            t=e["t"],
            rid=f"e{i:05d}",
            cls=e["cls"].name,
            kind=e["cls"].kind,
            tenant=e["tenant"],
            priority=e["cls"].priority,
            session=e["session"],
            turn=e["turn"],
            messages=e["messages"],
            prompt=e["prompt"],
            max_tokens=e["max_tokens"],
            stream=e["cls"].stream,
            temperature=e["cls"].temperature,
            seed=e["seed"],
            ttft_slo_ms=e["cls"].ttft_slo_ms,
            tpot_slo_ms=e["cls"].tpot_slo_ms,
        )
        for i, e in enumerate(raw)
    )
    return EventSchedule(spec=spec, seed=seed, events=events)
