"""Deterministic open-loop traffic replay + goodput-under-SLO soak.

The regression spine for the serving stack (ROADMAP item 5): a seeded,
declarative workload — Poisson or diurnal arrivals across tenant
classes with QoS priorities, multi-turn chat sessions with shared
prefixes alongside one-shot batch completions — compiled into a
replayable event schedule (a workload is a **pure function of its
seed**, the ``DTPU_FAULT_PLAN`` design contract), fired **open-loop**
by an asyncio driver (requests go out at schedule time regardless of
completions — the arrival pattern closed-loop benches can't produce,
and the one that exposes queueing collapse), and scored as **goodput
under SLO**: per-tenant-class completions meeting their TTFT/TPOT
targets, with honest-shed accounting (a 429 with a monotone
Retry-After is QoS working; a 5xx or truncated stream is always a
failure) and tail amplification across injected chaos windows.

``python -m dstack_tpu.loadgen --seed N`` stands up ≥2 real in-process
replicas behind the real :mod:`dstack_tpu.routing` forwarder with QoS
enabled, optionally kills a replica mid-soak (fault-plan driven, the
mid-stream resume path) and flips another DRAINING, and writes a
``SOAK_rNN.json`` artifact. See docs/guides/serving.md §11.

Layout (the generator path — spec/schedule/report/metrics — is
import-light: no jax, no aiohttp, no numpy; the driver and soak
runner import their runtimes lazily):

- :mod:`~dstack_tpu.loadgen.spec` — declarative workload spec.
- :mod:`~dstack_tpu.loadgen.textgen` — the ONE seeded text/prompt
  generator set (``serve/bench.py`` draws from the same functions).
- :mod:`~dstack_tpu.loadgen.schedule` — (spec, seed) → event schedule.
- :mod:`~dstack_tpu.loadgen.report` — SLO evaluator / soak artifact.
- :mod:`~dstack_tpu.loadgen.metrics` — ``dtpu_loadgen_*`` families.
- :mod:`~dstack_tpu.loadgen.driver` — asyncio open-loop driver (aiohttp).
- :mod:`~dstack_tpu.loadgen.soak` — full-stack soak runner (jax).
"""

from dstack_tpu.loadgen.metrics import (
    OUTCOMES,
    get_loadgen_registry,
    new_loadgen_registry,
)
from dstack_tpu.loadgen.report import (
    EventWindow,
    RequestRecord,
    evaluate,
    percentile,
)
from dstack_tpu.loadgen.schedule import (
    Event,
    EventSchedule,
    compile_schedule,
)
from dstack_tpu.loadgen.spec import (
    ArrivalSpec,
    TenantClass,
    WorkloadSpec,
    default_spec,
    load_spec,
    spec_from_dict,
    validate_spec,
)

__all__ = [
    "ArrivalSpec",
    "Event",
    "EventSchedule",
    "EventWindow",
    "OUTCOMES",
    "RequestRecord",
    "TenantClass",
    "WorkloadSpec",
    "compile_schedule",
    "default_spec",
    "evaluate",
    "get_loadgen_registry",
    "load_spec",
    "new_loadgen_registry",
    "percentile",
    "spec_from_dict",
    "validate_spec",
]
