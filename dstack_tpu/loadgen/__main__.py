"""``python -m dstack_tpu.loadgen`` — compile, soak, report.

Default run: compile the stock workload for ``--duration`` seconds at
``--rate`` rps from ``--seed``, stand up ``--replicas`` real replicas
behind the real router with QoS on, fire the open-loop schedule with
the mid-soak drain flip + replica kill enabled, and write
``SOAK_r01.json``. Two invocations with the same seed produce
byte-identical event schedules (the artifact's ``schedule_digest``
proves it; ``--schedule-only`` dumps the JSONL itself for a direct
diff).
"""

import argparse
import json
import sys

from dstack_tpu.loadgen.schedule import compile_schedule
from dstack_tpu.loadgen.spec import default_spec, load_spec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dstack_tpu.loadgen",
        description="deterministic open-loop traffic-replay soak "
                    "(goodput under SLO; docs/guides/serving.md §11)",
    )
    p.add_argument("--seed", type=int, default=0,
                   help="workload seed: the schedule is a pure function "
                        "of (spec, seed)")
    p.add_argument("--duration", type=float, default=75.0,
                   help="soak length in seconds (default 75)")
    p.add_argument("--rate", type=float, default=3.0,
                   help="mean open-loop request rate (requests/s)")
    p.add_argument("--spec", default=None,
                   help="workload spec: inline JSON or @/path.json "
                        "(default: the stock interactive/standard/batch "
                        "mix at --duration/--rate)")
    p.add_argument("--replicas", type=int, default=2,
                   help="in-process replicas behind the router (>= 2)")
    p.add_argument("--model", default="llama-tiny")
    p.add_argument("--qos-rps", type=float, default=2.0,
                   help="per-tenant QoS bucket rate at each serve edge")
    p.add_argument("--qos-burst", type=float, default=6.0)
    p.add_argument("--no-chaos", action="store_true",
                   help="skip the mid-soak drain flip and replica kill")
    p.add_argument("--scale-up", action="store_true",
                   help="mid-soak, boot a COLD extra replica under the "
                        "boot recorder and join it to the pool; the "
                        "artifact gains a `boot` block decomposing its "
                        "time-to-first-served-token (BOOT_rNN baseline)")
    p.add_argument("--scale-up-frac", type=float, default=0.45,
                   help="when to spawn the cold replica (fraction of "
                        "duration)")
    p.add_argument("--kill-frac", type=float, default=0.60,
                   help="when to kill a replica (fraction of duration)")
    p.add_argument("--drain-frac", type=float, nargs=2,
                   default=(0.25, 0.40), metavar=("START", "END"),
                   help="DRAINING window for one replica (fractions)")
    p.add_argument("--output", default="SOAK_r01.json",
                   help="artifact path ('' = print only)")
    p.add_argument("--schedule-only", action="store_true",
                   help="compile and print the event schedule JSONL, "
                        "run nothing (determinism check: diff two runs)")
    p.add_argument("--validate-spec", action="store_true",
                   help="validate --spec offline and exit")
    args = p.parse_args(argv)

    if args.validate_spec:
        from dstack_tpu.loadgen.spec import validate_spec

        raw = args.spec or "{}"
        data = (
            json.load(open(raw[1:]))
            if raw.startswith("@")
            else json.loads(raw)
        )
        errors = validate_spec(data)
        for e in errors:
            print(f"error: {e}", file=sys.stderr)
        print("spec ok" if not errors else f"{len(errors)} problem(s)")
        return 1 if errors else 0

    spec = (
        load_spec(args.spec)
        if args.spec
        else default_spec(duration_s=args.duration, rate_rps=args.rate)
    )
    schedule = compile_schedule(spec, args.seed)
    if args.schedule_only:
        sys.stdout.write(schedule.to_jsonl())
        print(
            f"# events={len(schedule.events)} seed={args.seed} "
            f"digest={schedule.digest()}",
            file=sys.stderr,
        )
        return 0

    # the soak runtime (jax + aiohttp) loads only past this point —
    # schedule compilation and validation stay import-light
    from dstack_tpu.loadgen.soak import SoakConfig, run_soak

    cfg = SoakConfig(
        replicas=args.replicas,
        model=args.model,
        qos_rps=args.qos_rps,
        qos_burst=args.qos_burst,
        chaos=not args.no_chaos,
        scale_up=args.scale_up,
        scale_up_frac=args.scale_up_frac,
        drain_start_frac=args.drain_frac[0],
        drain_end_frac=args.drain_frac[1],
        kill_frac=args.kill_frac,
        output=args.output or None,
    )
    result = run_soak(schedule, cfg)
    print(json.dumps({
        k: result[k]
        for k in (
            "metric", "value", "unit", "seed", "schedule_digest",
            "events", "duration_s", "replicas", "backend", "note",
            "failures", "client_5xx", "router",
        )
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
