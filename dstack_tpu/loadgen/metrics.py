"""Loadgen metric families (``dtpu_loadgen_*``, obs registry factory).

One construction point for every series the traffic-replay driver
exports, used by:

- :mod:`dstack_tpu.loadgen.driver` — per-request outcome/latency
  accounting at the source.
- ``python -m dstack_tpu.loadgen`` — renders the registry into the
  soak artifact's ``loadgen_metrics`` field (Prometheus text).
- the DTPU004 docs-coverage collector — enumerates the family names to
  hold docs/reference/server.md to account.

Import-light on purpose (no jax, no aiohttp): the docs checker and
unit tests instantiate the registry without a serving runtime.
"""

from typing import Optional

from dstack_tpu.obs import (
    LATENCY_BUCKETS_S,
    Registry,
    SHORT_LATENCY_BUCKETS_S,
)

#: bounded outcome enum for dtpu_loadgen_requests_total — the driver
#: classifies every fired event into exactly one of these
OUTCOMES = (
    "ok",  # completed (stream saw [DONE] / JSON body landed)
    "shed",  # honest 429 (QoS working, not a failure)
    "client_error",  # other 4xx (a workload bug, not the stack's)
    "failed_5xx",  # 5xx answer — ALWAYS a defect under this harness
    "failed_connect",  # connect/send error before any response
    "failed_truncated",  # response died mid-body without [DONE]
    "failed_stream_error",  # in-band terminal SSE error event
    "abandoned",  # still in flight when the drain timeout expired
)


def new_loadgen_registry() -> Registry:
    """Registry pre-populated with every loadgen metric family."""
    r = Registry()
    r.counter(
        "dtpu_loadgen_events_fired_total",
        "Schedule events fired by the open-loop driver (incremented at "
        "send time, before any response — a mid-soak scrape shows "
        "arrival progress)",
    )
    r.counter(
        "dtpu_loadgen_requests_total",
        "Fired requests by terminal outcome (ok / shed / client_error "
        "/ failed_5xx / failed_connect / failed_truncated / "
        "failed_stream_error / abandoned)",
        labelnames=("outcome",),
    )
    r.histogram(
        "dtpu_loadgen_ttft_seconds",
        "Client-observed time-to-first-token: request send to first "
        "non-empty content delta (streaming) or to the full response "
        "(non-streaming) — includes router, QoS, queueing, and prefill",
        buckets=LATENCY_BUCKETS_S,
    )
    r.histogram(
        "dtpu_loadgen_tpot_seconds",
        "Client-observed time-per-output-token: mean inter-delta gap "
        "over a completed stream with at least two content deltas",
        buckets=SHORT_LATENCY_BUCKETS_S,
    )
    r.histogram(
        "dtpu_loadgen_sched_lag_seconds",
        "Open-loop fidelity: how late each event fired relative to its "
        "compiled schedule time (a growing lag means the DRIVER is "
        "saturated and the workload is no longer open-loop)",
        buckets=SHORT_LATENCY_BUCKETS_S,
    )
    r.gauge(
        "dtpu_loadgen_inflight",
        "Requests the driver has fired and not yet resolved",
    )
    return r


_registry: Optional[Registry] = None


def get_loadgen_registry() -> Registry:
    """The process-global loadgen registry (driver and soak CLI share
    it; tests may construct their own via new_loadgen_registry)."""
    global _registry
    if _registry is None:
        _registry = new_loadgen_registry()
    return _registry
