"""Asyncio open-loop driver: fire the schedule, record the truth.

The driver is the "open" in open-loop: every event fires at its
compiled schedule time **regardless of what happened to earlier
requests** — no back-pressure coupling, no waiting for completions, no
retry loops. When the stack under test slows down, requests pile up
against it exactly like production arrivals would, which is the
queueing behavior a closed-loop client (one outstanding request per
virtual user) structurally cannot produce. The only honesty check the
driver applies to *itself* is schedule lag (``dtpu_loadgen_sched_lag_
seconds``): if the driver cannot keep up, the report says so instead
of silently thinning the workload.

Per fired request it records (:class:`~dstack_tpu.loadgen.report.
RequestRecord`): client-observed TTFT (send → first non-empty content
delta), TPOT (mean inter-delta gap), token count, terminal outcome
(``metrics.OUTCOMES``), and the 429 ``Retry-After`` hint for the
report's honest-shed accounting. SSE streams are parsed event-wise: a
``[DONE]``-terminated stream is ``ok``, an in-band ``error`` event is
``failed_stream_error``, and a connection death without ``[DONE]`` is
``failed_truncated`` — the exact truncation the router's mid-stream
resume exists to prevent.

This module imports aiohttp (keep it OUT of the package's import-light
generator path — ``dstack_tpu.loadgen`` imports it lazily).
"""

import asyncio
import json
import time
from typing import Callable, Dict, List, Optional, Sequence

import aiohttp

from dstack_tpu.loadgen.metrics import get_loadgen_registry
from dstack_tpu.obs.tracing import TRACE_HEADER
from dstack_tpu.loadgen.report import RequestRecord
from dstack_tpu.loadgen.schedule import Event
from dstack_tpu.utils.logging import get_logger

logger = get_logger("loadgen.driver")

#: how long past the last event the driver waits for stragglers before
#: recording them as ``abandoned`` (generous: covers a full generation
#: plus a failover/resume leg)
DEFAULT_DRAIN_S = 30.0


def default_payload(event: Event, model: str) -> dict:
    """The OpenAI-shaped request body for one event. The soak runner
    wraps this to add model-specific extras (e.g. a ``logit_bias``
    pinning a byte tokenizer to ASCII so resumed streams splice
    exactly)."""
    p: dict = {
        "model": model,
        "max_tokens": event.max_tokens,
        "temperature": event.temperature,
    }
    if event.kind == "chat":
        p["messages"] = list(event.messages or ())
    else:
        p["prompt"] = event.prompt or ""
    if event.stream:
        p["stream"] = True
    if event.seed is not None:
        p["seed"] = event.seed
    if event.priority:
        p["priority"] = event.priority
    return p


class _SSETally:
    """Incremental SSE parse of one response body: counts content
    deltas and spots terminal markers, without buffering the stream."""

    __slots__ = ("buf", "deltas", "done", "error", "finished")

    def __init__(self):
        self.buf = b""
        self.deltas = 0  # non-empty content deltas seen
        self.done = False  # [DONE] sentinel arrived
        self.error: Optional[str] = None  # in-band error event
        self.finished = False  # a finish_reason chunk arrived

    def feed(self, chunk: bytes) -> int:
        """→ number of new non-empty content deltas in this chunk."""
        self.buf += chunk
        new = 0
        while True:
            i = self.buf.find(b"\n\n")
            if i < 0:
                return new
            block, self.buf = self.buf[:i], self.buf[i + 2:]
            data_lines = [
                ln[5:].strip()
                for ln in block.split(b"\n")
                if ln.startswith(b"data:")
            ]
            if not data_lines:
                continue
            data = b"\n".join(data_lines)
            if data == b"[DONE]":
                self.done = True
                continue
            try:
                obj = json.loads(data)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            if "error" in obj and "choices" not in obj:
                detail = obj.get("error")
                if isinstance(detail, dict):
                    detail = detail.get("message") or str(detail)
                self.error = str(detail)
                continue
            choices = obj.get("choices")
            if isinstance(choices, list) and choices:
                c0 = choices[0]
                if isinstance(c0, dict):
                    delta = c0.get("delta")
                    text = (
                        delta.get("content")
                        if isinstance(delta, dict)
                        else c0.get("text")
                    )
                    if text:
                        new += 1
                        self.deltas += 1
                    if c0.get("finish_reason"):
                        self.finished = True
        # not reached


def _retry_after(resp) -> Optional[float]:
    raw = resp.headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


class OpenLoopDriver:
    """Fires a compiled schedule at a base URL and collects records.

    ``payload_for(event)`` builds each request body; ``headers_for
    (event)`` the per-request headers (the soak runner uses it to carry
    the tenant identity the router re-asserts as ``X-DTPU-Tenant``,
    exactly like an authenticated edge would)."""

    def __init__(
        self,
        base_url: str,
        payload_for: Callable[[Event], dict],
        headers_for: Optional[Callable[[Event], Dict[str, str]]] = None,
        drain_s: float = DEFAULT_DRAIN_S,
        request_timeout_s: float = 120.0,
        registry=None,
    ):
        self.base_url = base_url.rstrip("/")
        self.payload_for = payload_for
        self.headers_for = headers_for or (lambda e: {})
        self.drain_s = drain_s
        self.request_timeout_s = request_timeout_s
        # callers that embed the render in a per-run artifact pass a
        # fresh registry so back-to-back soaks in one process can't
        # leak each other's counts; the process-global default serves
        # ad-hoc driving
        self.metrics = (
            registry if registry is not None else get_loadgen_registry()
        )

    async def run(self, events: Sequence[Event]) -> List[RequestRecord]:
        """Fire every event at its schedule time → records (one per
        event, schedule order)."""
        m = self.metrics
        records: List[RequestRecord] = []
        loop = asyncio.get_running_loop()
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.request_timeout_s)
        ) as session:
            t0 = loop.time()
            tasks = []
            for ev in events:
                delay = t0 + ev.t - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                m.family("dtpu_loadgen_events_fired_total").inc(1)
                tasks.append(
                    asyncio.ensure_future(
                        self._fire(session, ev, t0, records)
                    )
                )
            if tasks:
                done, pending = await asyncio.wait(
                    tasks, timeout=self.drain_s
                )
                for p in pending:
                    p.cancel()
                if pending:
                    await asyncio.gather(*pending, return_exceptions=True)
        # schedule order, not lexicographic rid order (rids pad to 5
        # digits; a >100k-event schedule would interleave e100000
        # between e10000 and e10001 under a string sort)
        records.sort(key=lambda r: (r.t_sched, r.rid))
        return records

    async def _fire(
        self, session, ev: Event, t0: float, records: List[RequestRecord]
    ) -> None:
        loop = asyncio.get_running_loop()
        m = self.metrics
        t_sent = loop.time() - t0
        m.family("dtpu_loadgen_sched_lag_seconds").observe(
            max(0.0, t_sent - ev.t)
        )
        m.family("dtpu_loadgen_inflight").inc(1)
        rec = RequestRecord(
            rid=ev.rid, cls=ev.cls, tenant=ev.tenant,
            t_sched=ev.t, t_sent=t_sent, outcome="abandoned",
            session=ev.session, turn=ev.turn,
        )
        path = (
            "/v1/chat/completions" if ev.kind == "chat"
            else "/v1/completions"
        )
        try:
            await self._request(session, ev, path, rec)
        except asyncio.CancelledError:
            rec.outcome = "abandoned"
            rec.detail = "still in flight at drain timeout"
        except (aiohttp.ClientError, OSError) as e:
            if rec.ttft_s is None and rec.status is None:
                rec.outcome = "failed_connect"
            else:
                rec.outcome = "failed_truncated"
            rec.detail = repr(e)
        except asyncio.TimeoutError:
            rec.outcome = (
                "failed_connect" if rec.status is None
                else "failed_truncated"
            )
            rec.detail = "client request timeout"
        except Exception as e:  # noqa: BLE001 - the record IS the report
            # anything unexpected (e.g. a 200 whose body isn't JSON
            # from a misbehaving edge) must surface as a classified
            # failure with its detail, never masquerade as a
            # drain-timeout 'abandoned' straggler
            rec.outcome = (
                "failed_connect" if rec.status is None
                else "failed_truncated"
            )
            rec.detail = f"unexpected: {e!r}"
        finally:
            m.family("dtpu_loadgen_inflight").inc(-1)
            m.family("dtpu_loadgen_requests_total").inc(1, rec.outcome)
            if rec.ttft_s is not None:
                m.family("dtpu_loadgen_ttft_seconds").observe(rec.ttft_s)
            if rec.tpot_s is not None:
                m.family("dtpu_loadgen_tpot_seconds").observe(rec.tpot_s)
            records.append(rec)

    async def _request(self, session, ev: Event, path, rec) -> None:
        send = time.perf_counter()
        async with session.post(
            self.base_url + path,
            json=self.payload_for(ev),
            headers=self.headers_for(ev),
        ) as resp:
            rec.status = resp.status
            # the router's trace-id echo: links this record to its
            # distributed trace for the report's tail attribution
            rec.trace_id = resp.headers.get(TRACE_HEADER)
            if resp.status == 429:
                rec.outcome = "shed"
                rec.retry_after = _retry_after(resp)
                await resp.read()
                return
            if resp.status >= 500:
                rec.outcome = "failed_5xx"
                rec.detail = (await resp.text())[:200]
                return
            if resp.status >= 400:
                rec.outcome = "client_error"
                rec.detail = (await resp.text())[:200]
                return
            ctype = resp.headers.get("Content-Type", "")
            if not ctype.startswith("text/event-stream"):
                body = await resp.json(content_type=None)
                rec.ttft_s = time.perf_counter() - send
                usage = (
                    body.get("usage") if isinstance(body, dict) else None
                )
                if isinstance(usage, dict):
                    rec.tokens = int(usage.get("completion_tokens") or 0)
                rec.outcome = "ok"
                return
            tally = _SSETally()
            first = last = None
            async for chunk in resp.content.iter_chunked(16 * 1024):
                if tally.feed(chunk):
                    now = time.perf_counter()
                    if first is None:
                        first = now
                    last = now
            rec.tokens = tally.deltas
            if first is not None:
                rec.ttft_s = first - send
                if tally.deltas >= 2 and last is not None:
                    rec.tpot_s = (last - first) / (tally.deltas - 1)
            if tally.error is not None:
                # the honest terminal event the forwarder emits when a
                # stream could not be resumed — a failure by contract
                rec.outcome = "failed_stream_error"
                rec.detail = tally.error[:200]
            elif tally.done:
                rec.outcome = "ok"
            else:
                rec.outcome = "failed_truncated"
                rec.detail = "stream ended without [DONE]"
