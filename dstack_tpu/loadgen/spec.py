"""Declarative workload spec for the traffic-replay soak harness.

A :class:`WorkloadSpec` describes production-shaped traffic as data —
an arrival process, a tenant-class mix, and per-class request shapes —
and the schedule compiler (:mod:`dstack_tpu.loadgen.schedule`) turns
(spec, seed) into a replayable event schedule. The spec deliberately
contains **no randomness**: every draw happens in the compiler from
named ``random.Random`` streams, so a workload is a pure function of
its seed (the ``DTPU_FAULT_PLAN`` design contract).

Two request kinds:

- ``chat`` — multi-turn conversations with shared prefixes: each class
  arrival *starts a session*; the session's later turns follow at
  seeded think-time gaps, and turn *k+1*'s message list extends turn
  *k*'s (user turns and scripted assistant turns are both seeded text,
  so the prefix chain — and therefore prefix-affinity routing and the
  engine's KV prefix cache — behaves like a real conversation replay
  without coupling the schedule to live completions).
- ``completion`` — one-shot batch completions (a single prompt string).

Per-class SLO targets (``ttft_slo_ms``/``tpot_slo_ms``) are what the
report evaluator scores **goodput** against: a request counts toward
goodput only when it completed successfully AND met both targets
(DistServe's goodput-under-SLO, not raw throughput).

Validation follows :func:`dstack_tpu.faults.validate_plan`'s style:
offline, returns a list of error strings, raises nothing until a
caller actually compiles.

Import-light on purpose (stdlib only): the schedule compiler, the docs
tooling, and unit tests load this without aiohttp or jax.
"""

import json
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from dstack_tpu.loadgen.textgen import bounds_pair
# the one SLO-target schema: per-class ttft_slo_ms/tpot_slo_ms
# defaults and validation live in obs/slo.py (stdlib-only, so this
# module stays import-light) — the live burn engine's SLOPolicy
# classes and these tenant classes cannot drift
from dstack_tpu.obs.slo import (
    DEFAULT_TPOT_SLO_MS,
    DEFAULT_TTFT_SLO_MS,
    validate_slo_target_fields,
)

_KINDS = ("chat", "completion")
_PROCESSES = ("poisson", "diurnal")
_PRIORITIES = ("interactive", "standard", "batch")


@dataclass(frozen=True)
class ArrivalSpec:
    """Open-loop arrival process for the whole workload.

    ``rate_rps`` is the mean REQUEST rate across all classes (chat
    turns count as requests: a chat class admits sessions at
    ``share * rate / turns`` so its turn stream lands near its share).
    ``diurnal`` modulates the rate sinusoidally: rate(t) =
    rate × (1 + amplitude × sin(2πt / period_s)), realized by seeded
    thinning of a peak-rate Poisson stream — still a pure function of
    the seed."""

    process: str = "poisson"
    rate_rps: float = 3.0
    amplitude: float = 0.5  # diurnal only; peak = rate × (1 + amplitude)
    period_s: float = 60.0


@dataclass(frozen=True)
class TenantClass:
    """One tenant class: its share of traffic, QoS priority, SLO
    targets, and request shape."""

    name: str
    kind: str = "chat"  # "chat" | "completion"
    share: float = 1.0  # relative weight of the arrival mix
    tenants: int = 2  # distinct tenant identities in this class
    priority: str = "standard"  # serve-edge priority class
    ttft_slo_ms: float = DEFAULT_TTFT_SLO_MS
    tpot_slo_ms: float = DEFAULT_TPOT_SLO_MS
    stream: bool = True
    temperature: float = 0.0  # 0 = greedy (resumable mid-stream)
    seeded: bool = False  # temperature > 0 with a per-request seed
    max_tokens: Tuple[int, int] = (4, 12)  # inclusive range
    # chat shape
    turns: int = 3
    think_time_s: float = 3.0  # mean exponential gap between turns
    turn_chars: Tuple[int, int] = (80, 200)
    # completion shape
    prompt_chars: Tuple[int, int] = (200, 600)


@dataclass(frozen=True)
class WorkloadSpec:
    duration_s: float = 60.0
    arrival: ArrivalSpec = field(default_factory=ArrivalSpec)
    classes: Tuple[TenantClass, ...] = ()

    def to_dict(self) -> dict:
        return {
            "duration_s": self.duration_s,
            "arrival": {
                "process": self.arrival.process,
                "rate_rps": self.arrival.rate_rps,
                "amplitude": self.arrival.amplitude,
                "period_s": self.arrival.period_s,
            },
            "classes": [
                {
                    "name": c.name,
                    "kind": c.kind,
                    "share": c.share,
                    "tenants": c.tenants,
                    "priority": c.priority,
                    "ttft_slo_ms": c.ttft_slo_ms,
                    "tpot_slo_ms": c.tpot_slo_ms,
                    "stream": c.stream,
                    "temperature": c.temperature,
                    "seeded": c.seeded,
                    "max_tokens": list(c.max_tokens),
                    "turns": c.turns,
                    "think_time_s": c.think_time_s,
                    "turn_chars": list(c.turn_chars),
                    "prompt_chars": list(c.prompt_chars),
                }
                for c in self.classes
            ],
        }


def validate_spec(data) -> List[str]:
    """Offline spec validation → list of error strings (empty = valid).
    Mirrors ``faults.validate_plan``: shape and enum checks only, no
    compilation, nothing imported."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return [f"spec must be a JSON object, got {type(data).__name__}"]
    unknown = set(data) - {"duration_s", "arrival", "classes"}
    if unknown:
        errors.append(f"unknown top-level keys: {sorted(unknown)}")
    dur = data.get("duration_s", 60.0)
    if not isinstance(dur, (int, float)) or dur <= 0:
        errors.append(f"duration_s must be a positive number, got {dur!r}")
    arrival = data.get("arrival", {})
    if not isinstance(arrival, dict):
        errors.append("arrival must be an object")
        arrival = {}
    unknown_arrival = set(arrival) - {
        "process", "rate_rps", "amplitude", "period_s",
    }
    if unknown_arrival:
        errors.append(f"unknown arrival keys: {sorted(unknown_arrival)}")
    proc = arrival.get("process", "poisson")
    if proc not in _PROCESSES:
        errors.append(f"arrival.process {proc!r} not one of {_PROCESSES}")
    rate = arrival.get("rate_rps", 3.0)
    if not isinstance(rate, (int, float)) or rate <= 0:
        errors.append(f"arrival.rate_rps must be positive, got {rate!r}")
    period = arrival.get("period_s", 60.0)
    if not isinstance(period, (int, float)) or period <= 0:
        errors.append(f"arrival.period_s must be positive, got {period!r}")
    amp = arrival.get("amplitude", 0.5)
    if not isinstance(amp, (int, float)) or not 0.0 <= amp <= 1.0:
        errors.append(
            f"arrival.amplitude must be in [0, 1], got {amp!r}"
        )
    classes = data.get("classes")
    if classes is None:
        return errors + ["classes is required (at least one tenant class)"]
    if not isinstance(classes, list) or not classes:
        return errors + ["classes must be a non-empty list"]
    known_class_keys = {
        "name", "kind", "share", "tenants", "priority", "ttft_slo_ms",
        "tpot_slo_ms", "stream", "temperature", "seeded", "max_tokens",
        "turns", "think_time_s", "turn_chars", "prompt_chars",
    }
    for i, c in enumerate(classes):
        where = f"classes[{i}]"
        if not isinstance(c, dict):
            errors.append(f"{where}: must be an object")
            continue
        unknown_cls = set(c) - known_class_keys
        if unknown_cls:
            # a typo'd SLO field silently scoring against the default
            # target would be the worst kind of green: reject it, like
            # faults.validate_plan rejects unknown rule keys
            errors.append(f"{where}: unknown keys {sorted(unknown_cls)}")
        if not isinstance(c.get("name"), str) or not c.get("name"):
            errors.append(f"{where}: 'name' is required")
        kind = c.get("kind", "chat")
        if kind not in _KINDS:
            errors.append(f"{where}: kind {kind!r} not one of {_KINDS}")
        prio = c.get("priority", "standard")
        if prio not in _PRIORITIES:
            errors.append(
                f"{where}: priority {prio!r} not one of {_PRIORITIES}"
            )
        share = c.get("share", 1.0)
        if not isinstance(share, (int, float)) or share <= 0:
            errors.append(f"{where}: share must be positive, got {share!r}")
        tenants = c.get("tenants", 2)
        if not isinstance(tenants, int) or tenants < 1:
            errors.append(f"{where}: tenants must be an int >= 1")
        turns = c.get("turns", 3)
        if kind == "chat" and (not isinstance(turns, int) or turns < 1):
            errors.append(f"{where}: turns must be an int >= 1")
        # shared SLO-target validation (obs/slo.py: the same checker
        # SLOPolicy classes run through)
        errors.extend(validate_slo_target_fields(c, where))
        v = c.get("think_time_s")
        if v is not None and (not isinstance(v, (int, float)) or v <= 0):
            errors.append(
                f"{where}: think_time_s must be positive, got {v!r}"
            )
        for key in ("max_tokens", "turn_chars", "prompt_chars"):
            v = c.get(key)
            if v is None or isinstance(v, int):
                continue
            if not (
                isinstance(v, list)
                and len(v) == 2
                and all(isinstance(x, int) and x > 0 for x in v)
            ):
                errors.append(
                    f"{where}: {key} must be an int or [lo, hi] of "
                    f"positive ints, got {v!r}"
                )
        if c.get("seeded") and float(c.get("temperature") or 0.0) <= 0.0:
            errors.append(
                f"{where}: seeded=true needs temperature > 0 "
                "(greedy requests carry no sampling seed)"
            )
    names = [c.get("name") for c in classes if isinstance(c, dict)]
    if len(names) != len(set(names)):
        errors.append("class names must be unique")
    return errors


def spec_from_dict(data: dict) -> WorkloadSpec:
    """Parse + validate → :class:`WorkloadSpec`; raises ``ValueError``
    listing every problem (same failure mode as a bad fault plan: loud
    and before any replica is stood up)."""
    errors = validate_spec(data)
    if errors:
        raise ValueError("invalid workload spec: " + "; ".join(errors))
    arrival = data.get("arrival", {})
    classes = []
    for c in data["classes"]:
        classes.append(
            TenantClass(
                name=c["name"],
                kind=c.get("kind", "chat"),
                share=float(c.get("share", 1.0)),
                tenants=int(c.get("tenants", 2)),
                priority=c.get("priority", "standard"),
                ttft_slo_ms=float(c.get("ttft_slo_ms", DEFAULT_TTFT_SLO_MS)),
                tpot_slo_ms=float(c.get("tpot_slo_ms", DEFAULT_TPOT_SLO_MS)),
                stream=bool(c.get("stream", True)),
                temperature=float(c.get("temperature", 0.0)),
                seeded=bool(c.get("seeded", False)),
                max_tokens=bounds_pair(c.get("max_tokens"), (4, 12)),
                turns=int(c.get("turns", 3)),
                think_time_s=float(c.get("think_time_s", 3.0)),
                turn_chars=bounds_pair(c.get("turn_chars"), (80, 200)),
                prompt_chars=bounds_pair(c.get("prompt_chars"), (200, 600)),
            )
        )
    return WorkloadSpec(
        duration_s=float(data.get("duration_s", 60.0)),
        arrival=ArrivalSpec(
            process=arrival.get("process", "poisson"),
            rate_rps=float(arrival.get("rate_rps", 3.0)),
            amplitude=float(arrival.get("amplitude", 0.5)),
            period_s=float(arrival.get("period_s", 60.0)),
        ),
        classes=tuple(classes),
    )


def load_spec(text: str) -> WorkloadSpec:
    """Spec from inline JSON or ``@/path.json`` (the fault-plan
    convention)."""
    text = text.strip()
    if text.startswith("@"):
        with open(text[1:]) as f:
            return spec_from_dict(json.load(f))
    return spec_from_dict(json.loads(text))


def default_spec(
    duration_s: float = 75.0, rate_rps: float = 3.0
) -> WorkloadSpec:
    """The stock soak mix: interactive multi-turn chat (tight SLOs),
    standard chat, and one-shot batch completions (loose SLOs) — the
    "long multi-turn chats alongside batch completions" shape the
    roadmap's million-user envelope names. All classes are greedy so
    every stream is resumable across a mid-soak replica death."""
    return spec_from_dict({
        "duration_s": duration_s,
        "arrival": {"process": "poisson", "rate_rps": rate_rps},
        "classes": [
            {
                "name": "interactive",
                "kind": "chat",
                "share": 0.5,
                "tenants": 2,
                "priority": "interactive",
                "ttft_slo_ms": 2500.0,
                "tpot_slo_ms": 400.0,
                "turns": 4,
                "think_time_s": max(2.0, duration_s / 30.0),
                "turn_chars": [80, 200],
                "max_tokens": [4, 10],
            },
            {
                "name": "standard",
                "kind": "chat",
                "share": 0.3,
                "tenants": 2,
                "priority": "standard",
                "ttft_slo_ms": 5000.0,
                "tpot_slo_ms": 800.0,
                "turns": 3,
                "think_time_s": max(2.0, duration_s / 25.0),
                "turn_chars": [60, 160],
                "max_tokens": [4, 10],
            },
            {
                "name": "batch",
                "kind": "completion",
                "share": 0.2,
                "tenants": 1,
                "priority": "batch",
                "ttft_slo_ms": 15000.0,
                "tpot_slo_ms": 2000.0,
                "prompt_chars": [200, 500],
                "max_tokens": [6, 16],
            },
        ],
    })
