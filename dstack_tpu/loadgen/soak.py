"""Full-stack soak: real replicas, real router, real QoS, real chaos.

The runner behind ``python -m dstack_tpu.loadgen``: stands up N (≥ 2)
REAL in-process replicas — each a live :class:`InferenceEngine` behind
its own :func:`serve.openai_server.build_app` with QoS admission
enabled — puts the REAL :func:`routing.forward.forward_with_failover`
over a :class:`routing.pool.ReplicaPool` in front of them (probe loop
included, exactly the production data path), fires the compiled
open-loop schedule through the router, and writes a ``SOAK_rNN.json``
artifact scoring goodput under SLO.

Mid-soak chaos, on by default:

- **Drain flip**: one replica is marked DRAINING partway in and put
  back in rotation (``cancel_draining``) at the window's end — the
  scale-down/upgrade shape; the picker must route around it with zero
  client-visible errors.
- **Replica kill**: later, a different replica "dies": a
  ``serve.stream`` fault rule (installed through the real
  :mod:`dstack_tpu.faults` plan machinery, merged into any active
  ``DTPU_FAULT_PLAN``) severs every in-flight and future stream chunk
  from that replica while its listener socket stops accepting — so
  in-flight streams take the PR-9 mid-stream resume path onto a
  survivor and new requests fail over, and the breaker converges the
  pool to DEAD. The replica's *process* survives (this is an
  in-process harness) but the router must treat it exactly like a
  death. The acceptance bar: **zero client 5xx through the kill**.

Both windows land in the report's tail-amplification block.

This module imports jax + aiohttp — keep it out of the package's
import-light generator path (``__main__`` imports it directly).
"""

import asyncio
import json
import socket
import time
from dataclasses import dataclass
from typing import List, Optional

from dstack_tpu.loadgen.report import EventWindow, evaluate
from dstack_tpu.loadgen.schedule import EventSchedule
from dstack_tpu.utils.logging import get_logger

logger = get_logger("loadgen.soak")

#: router metric families snapshotted into the artifact (delta over
#: the soak, so back-to-back runs in one process stay honest)
_ROUTER_FAMILIES = (
    "dtpu_router_failovers_total",
    "dtpu_router_stream_resumes_total",
    "dtpu_router_breaker_opens_total",
    "dtpu_router_exhausted_total",
    "dtpu_router_affinity_hits_total",
    "dtpu_router_affinity_overrides_total",
    "dtpu_router_slo_degraded_total",
    "dtpu_router_slo_restored_total",
)


@dataclass
class SoakConfig:
    """Everything about the soak that is NOT the workload (the
    workload lives in the spec; this is the stack under test)."""

    replicas: int = 2
    model: str = "llama-tiny"
    qos_rps: float = 2.0  # per-tenant bucket rate at each serve edge
    qos_burst: float = 6.0
    tenant_inflight: int = 0
    max_batch: int = 8
    max_seq: int = 2048
    prefill_chunk: int = 64
    probe_interval_s: float = 0.5
    # chaos (soak-relative fractions of the schedule duration)
    chaos: bool = True
    drain_start_frac: float = 0.25
    drain_end_frac: float = 0.40
    kill_frac: float = 0.60
    kill_window_s: float = 8.0  # scored amplification window after kill
    # extra fault rules merged into the plan AT kill time (rule
    # counters restart with the new plan, so nth counts from the kill)
    # — the SLO chaos acceptance injects bounded serve.engine.step
    # errors on a SURVIVOR here: clients ride the resume path, the
    # replica's own error counter burns its SLO
    kill_extra_rules: Optional[list] = None
    # scale-up (obs/boot.py): mid-soak a COLD extra replica is built
    # from nothing — params init, engine construction, HTTP warmup,
    # prefix-copy warm — under its own boot recorder, then joins the
    # pool via sync(); the artifact gains a `boot` block decomposing
    # its time-to-first-served-token by stage plus a scored
    # `scale_up` goodput/tail window around the join. This artifact
    # (BOOT_rNN.json) is the scale-out-latency baseline ROADMAP item
    # 4 optimizes against.
    scale_up: bool = False
    scale_up_frac: float = 0.45  # spawn at this fraction of the soak
    scale_up_window_s: float = 8.0  # scored window after the spawn
    # live SLO engine over the soak's own pool (obs/slo.py): a policy
    # dict turns it on — per-replica windows are ingested from the
    # probe loop's /health captures, burn alerts evaluated every
    # slo_tick_s, per-replica fast-burn firing pins the replica
    # DEGRADED exactly like the server's process_slo, and the artifact
    # gains an `slo` block with the transition timeline
    slo_policy: Optional[dict] = None
    slo_windows: Optional[dict] = None  # window name -> seconds (as-is)
    slo_tick_s: float = 0.5
    drain_s: float = 30.0  # driver straggler budget past the last event
    output: Optional[str] = "SOAK_r01.json"


class _Replica:
    __slots__ = ("rid", "engine", "app", "runner", "site", "port", "killed")

    def __init__(self, rid, engine, app, runner, site, port):
        self.rid = rid
        self.engine = engine
        self.app = app
        self.runner = runner
        self.site = site
        self.port = port
        self.killed = False


async def _start_replica(rid: str, engine, model: str, policy, boot=None):
    from aiohttp import web

    from dstack_tpu.serve.openai_server import build_app
    from dstack_tpu.serve.tokenizer import ByteTokenizer

    # boot=None keeps the harness replicas OFF the process-global boot
    # recorder (one process, many replicas — only the scale-up replica
    # carries one, and it brings its own)
    app = build_app(
        engine, ByteTokenizer(), model, qos_policy=policy, boot=boot,
    )
    runner = web.AppRunner(app)
    await runner.setup()
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    site = web.SockSite(runner, sock)
    await site.start()
    return _Replica(rid, engine, app, runner, site, port)


def _router_app(pool, session_holder):
    """The minimal production edge: every request forwarded through
    ``forward_with_failover``, with the soak's tenant identity
    re-asserted as the proxy-trusted ``X-DTPU-Tenant`` (the driver
    sends ``X-Soak-Tenant``; a real edge would derive it from auth —
    either way the client-supplied QoS header never passes through)."""
    from aiohttp import web

    from dstack_tpu import qos
    from dstack_tpu.routing.forward import forward_with_failover

    app = web.Application()

    async def handler(request):
        tenant = request.headers.get("X-Soak-Tenant") or "anonymous"
        return await forward_with_failover(
            request, pool, session_holder["session"],
            request.match_info["path"],
            extra_headers={qos.TENANT_HEADER: tenant},
        )

    app.router.add_route("*", "/{path:.*}", handler)
    return app


async def _probe_loop(pool, interval: float):
    import aiohttp

    async with aiohttp.ClientSession() as session:
        while True:
            targets = pool.probe_targets()
            if targets:
                await asyncio.gather(
                    *(pool.probe_replica(session, e) for e in targets),
                    return_exceptions=True,
                )
            await asyncio.sleep(interval)


async def _warmup(replicas: List[_Replica], model: str, bias: dict):
    """Compile every kernel the soak will hit, per replica, outside
    the timed schedule. The timed numbers must measure the stack, not
    XLA: that means covering not just one prompt but the shape
    *buckets* the schedule exercises — short and long chat prompts
    (different chunk counts), a completion prompt, a full-size decode
    budget, and CONCURRENT arrivals (the packed-prefill G=2/G=4
    variants compile only when a wave actually packs). Warmup text is
    then dropped from the prefix cache so the soak starts cold."""
    import aiohttp

    long_text = " ".join(f"warm{i}" for i in range(180))
    short_text = " ".join(f"warm{i}" for i in range(30))

    def _chat(text):
        return ("/v1/chat/completions", {
            "model": model, "max_tokens": 16, "stream": True,
            "temperature": 0.0, "logit_bias": bias,
            "messages": [{"role": "user", "content": text}],
        })

    def _completion(text):
        return ("/v1/completions", {
            "model": model, "max_tokens": 16, "stream": True,
            "temperature": 0.0, "logit_bias": bias, "prompt": text,
        })

    seq = iter(range(10_000))

    async def _one(session, base, path, payload):
        # one tenant per warmup request: warmup must never collide
        # with the replica's own QoS burst (a shed here would abort
        # the soak, and warmup traffic is not part of the workload)
        async with session.post(
            base + path, json=payload,
            headers={"X-DTPU-Tenant": f"warmup-{next(seq)}"},
        ) as resp:
            await resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"warmup {path} answered {resp.status}"
                )

    async with aiohttp.ClientSession() as session:
        for r in replicas:
            base = f"http://127.0.0.1:{r.port}"
            # serial pass: each shape bucket compiles alone
            for path, payload in (
                _chat(short_text), _chat(long_text),
                _completion(long_text),
            ):
                await _one(session, base, path, payload)
            # concurrent pass: four at once so prefill waves PACK and
            # the G>1 bucket variants compile now, not mid-soak
            await asyncio.gather(*(
                _one(session, base, path, dict(payload))
                for path, payload in (
                    _chat(short_text + " a"), _chat(short_text + " b"),
                    _chat(long_text + " a"), _chat(long_text + " b"),
                )
            ))
            r.engine.reset_prefix_cache()


async def _drain_flip(pool, rid: str, start: float, end: float):
    await asyncio.sleep(start)
    pool.mark_draining(rid)
    logger.warning("soak chaos: replica %s DRAINING at t=%.1fs", rid, start)
    await asyncio.sleep(max(0.0, end - start))
    pool.cancel_draining(rid)
    logger.warning("soak chaos: replica %s drain cancelled", rid)


async def _slo_loop(engine, pool, scope: str, interval: float):
    """The soak's in-process analogue of the server's process_slo
    loop — ingest, evaluate, pin — via the SAME obs.slo helpers the
    server uses, so the chaos acceptance exercises the production
    contract, not a reimplementation."""
    from dstack_tpu.obs import slo as obs_slo

    while True:
        obs_slo.ingest_pool_windows(engine, pool, scope)
        transitions = engine.evaluate()
        obs_slo.apply_replica_pins(pool, transitions, scope=scope)
        for tr in transitions:
            logger.warning(
                "soak slo_alert %s: %s %s%s burn=%.1fx",
                tr.state, tr.severity, tr.objective,
                f" replica={tr.replica}" if tr.replica else "", tr.burn,
            )
        await asyncio.sleep(interval)


async def _kill_replica(
    replica: _Replica, seed: int, at: float, extra_rules=None
):
    """The mid-soak death: merge a ``serve.stream`` connect-error rule
    for this replica into the active fault plan (the deterministic
    kill of every in-flight stream — the forwarder resumes them
    elsewhere), stop its listener (new connects fail over), and
    force-close its established connections (a dead process holds no
    keep-alive sockets — without this, pooled router and probe
    connections would keep reaching the 'corpse' and the breaker
    would never learn it died)."""
    from dstack_tpu import faults

    await asyncio.sleep(at)
    rules = []
    prior = faults.current_plan()
    if prior is not None:
        rules.extend(r.raw for r in prior.rules)
    rules.append({
        "point": "serve.stream",
        "ctx": {"replica": replica.rid},
        "action": "raise",
        "error": "connect",
    })
    if extra_rules:
        rules.extend(extra_rules)
    faults.install_plan({"seed": seed, "rules": rules})
    await replica.site.stop()
    if replica.runner.server is not None:
        # a SMALL positive timeout, then cancel in-progress handlers
        # and close their transports (aiohttp treats timeout=0 as "no
        # timeout" and would wait forever for in-flight streams — the
        # exact opposite of a death); the outer bound keeps a wedged
        # handler from stalling the chaos task itself
        try:
            await asyncio.wait_for(
                replica.runner.server.shutdown(timeout=0.05), timeout=2.0
            )
        except asyncio.TimeoutError:
            pass
    replica.killed = True
    logger.warning(
        "soak chaos: replica %s killed at t=%.1fs (listener stopped, "
        "connections severed, serve.stream fault installed)",
        replica.rid, at,
    )


async def _scale_up_replica(
    state: dict, replicas: List["_Replica"], pool, config, cfg,
    policy, bias: dict, at: float,
):
    """The mid-soak scale-up: build a COLD replica from nothing under
    its own boot recorder — params init (honest bytes: a fresh tree,
    not a shared reference), engine construction, listener, the same
    HTTP shape-bucket warmup the baseline replicas got, prefix-copy
    warm — then join the pool via sync(). From there the production
    machinery takes over: the probe loop's first /health answers the
    ``first_probe`` (time-to-ready) mark and ingests the boot block,
    and the first soak-workload token it serves seals TTFST.

    The recorder carries a PRIVATE registry: its replica-local
    histogram observations must not double-count against the pool's
    probe-ingested fleet aggregation living in the same process (in a
    real deployment those are different processes)."""
    import jax

    from dstack_tpu.models import llama
    from dstack_tpu.obs import boot as obs_boot
    from dstack_tpu.serve.engine import InferenceEngine

    await asyncio.sleep(at)
    rid = f"r{cfg.replicas}"
    rec = obs_boot.BootRecorder(registry=obs_boot.new_boot_registry())
    state["recorder"] = rec
    state["t_spawn"] = at
    logger.warning(
        "soak scale-up: spawning cold replica %s at t=%.1fs (boot %s)",
        rid, at, rec.boot_id,
    )
    with rec.stage("weights_load", source="init") as st:
        fresh = llama.init_params(config, jax.random.key(1))
        st.set(bytes=sum(
            int(x.nbytes) for x in jax.tree_util.tree_leaves(fresh)
        ))
    with rec.stage("engine_init"):
        engine = InferenceEngine(
            config, fresh, max_batch=cfg.max_batch,
            max_seq=cfg.max_seq, prefill_chunk=cfg.prefill_chunk,
        )
    engine.fault_ctx = {"replica": rid}
    replica = await _start_replica(
        rid, engine, cfg.model, policy, boot=rec,
    )
    # shared teardown list FIRST: if anything below fails, the soak's
    # finally block still stops this replica
    replicas.append(replica)
    state["engine"] = engine
    sched = replica.app["scheduler"]
    # warmup tokens are harness traffic, not the workload: suppress
    # the TTFST mark until the replica is in rotation, so the boot
    # block measures first token served THROUGH THE ROUTER
    sched._boot_served = True
    with rec.stage("warmup_compile") as st:
        await _warmup([replica], cfg.model, bias)
        st.set(manifest=len(engine.compile_manifest()))
    with rec.stage("warm_prefix_copies"):
        engine.warm_prefix_copies()
    engine.mark_flight_warm()
    sched._boot_served = False
    # join: re-sync with the full membership — existing entries keep
    # their probed health state, the newcomer starts STARTING and the
    # probe loop promotes it (its first probe is the READY mark)
    pool.sync(state["members"] + [(rid, "127.0.0.1", replica.port)])
    state["joined_at"] = time.monotonic()
    logger.warning(
        "soak scale-up: replica %s joined the pool (warm, %d manifest "
        "variants)", rid, len(engine.compile_manifest()),
    )


def _snapshot(registry, families) -> dict:
    return {name: registry.family(name).value() for name in families}


async def _soak_async(schedule: EventSchedule, cfg: SoakConfig) -> dict:
    import jax

    from dstack_tpu import faults, qos
    from dstack_tpu.loadgen.driver import OpenLoopDriver, default_payload
    from dstack_tpu.loadgen.metrics import new_loadgen_registry
    from dstack_tpu.models import llama
    from dstack_tpu.routing.metrics import get_router_registry
    from dstack_tpu.routing.pool import (
        PoolConfig,
        ReplicaPool,
        ReplicaState,
    )
    from dstack_tpu.serve.engine import InferenceEngine
    from dstack_tpu.utils.backend import backend_info

    spec, seed = schedule.spec, schedule.seed
    if cfg.replicas < 2:
        raise ValueError("soak needs >= 2 replicas: the point is routing")
    if cfg.chaos and cfg.replicas == 2 and cfg.drain_end_frac > cfg.kill_frac:
        # with two replicas, the drained one must be BACK IN ROTATION
        # before the other dies — overlapping windows would leave zero
        # routable replicas and report a harness-config artifact as a
        # stack failure
        raise ValueError(
            "chaos windows overlap with only 2 replicas: drain ends at "
            f"{cfg.drain_end_frac} but the kill fires at "
            f"{cfg.kill_frac}; end the drain first or add a third "
            "replica"
        )
    # size the trace ring to the whole schedule: the report attributes
    # each window's worst requests from the ring AFTER the soak, and
    # the default 256-trace buffer would evict the drain window's
    # traces long before then (warmup + per-request churn included)
    from dstack_tpu.obs import tracing as obs_tracing

    if obs_tracing.enabled():
        obs_tracing.enable(
            buffer=max(
                obs_tracing.get_tracer().buffer,
                4 * len(schedule.events) + 64,
            ),
            sample=1.0,
        )
    config = llama.CONFIGS[cfg.model]
    params = llama.init_params(config, jax.random.key(0))
    # pin the random-init model to ASCII output (ban non-byte ids incl.
    # eos): resumed streams splice delivered TEXT back into the prompt,
    # so output must round-trip the byte tokenizer exactly, and banning
    # eos keeps generations at their full token budget
    ascii_bias = {str(i): -100 for i in range(128, config.vocab_size)}
    policy = qos.QoSPolicy(
        rps=cfg.qos_rps, burst=cfg.qos_burst,
        tenant_inflight=cfg.tenant_inflight,
    )
    prior_plan = faults.current_plan()
    prior_rules = (
        {"seed": prior_plan.seed, "rules": [r.raw for r in prior_plan.rules]}
        if prior_plan is not None
        else None
    )
    replicas: List[_Replica] = []
    chaos_tasks: List[asyncio.Task] = []
    probe_task = None
    router_runner = None
    session_holder: dict = {"session": None}
    try:
        for i in range(cfg.replicas):
            engine = InferenceEngine(
                config, params, max_batch=cfg.max_batch,
                max_seq=cfg.max_seq, prefill_chunk=cfg.prefill_chunk,
            )
            # both engines share this process's fault plan: the replica
            # ctx lets a chaos rule target ONE of them (e.g. bounded
            # serve.engine.step errors on a survivor)
            engine.fault_ctx = {"replica": f"r{i}"}
            replicas.append(
                await _start_replica(f"r{i}", engine, cfg.model, policy)
            )
        pool = ReplicaPool("soak", "loadgen", PoolConfig(startup_grace=0.0))
        members = [
            ("r%d" % i, "127.0.0.1", r.port)
            for i, r in enumerate(replicas)
        ]
        pool.sync(members)
        # serial warmup traffic + optimistic-STARTING would pin every
        # request to the first success (READY outranks STARTING): start
        # READY like a probed pool; the probe loop maintains it from here
        for e in pool.entries.values():
            e.state = ReplicaState.READY
        router = await _start_router(pool, session_holder)
        router_runner = router
        probe_task = asyncio.ensure_future(
            _probe_loop(pool, cfg.probe_interval_s)
        )
        slo_engine = None
        if cfg.slo_policy is not None:
            from dstack_tpu.obs import slo as obs_slo

            if obs_slo.enabled():
                # scale=None: windows and hold-downs ride
                # DTPU_BG_TICK_SCALE exactly like the replicas' own
                # aggregators, so both sides window the same spans
                slo_engine = obs_slo.SLOEngine(
                    policy=obs_slo.policy_from_dict(cfg.slo_policy),
                    windows=cfg.slo_windows,
                    registry=obs_slo.new_slo_registry(),  # per-soak
                )
                slo_task = asyncio.ensure_future(_slo_loop(
                    slo_engine, pool, "soak/loadgen", cfg.slo_tick_s
                ))
                chaos_tasks.append(slo_task)
        await _warmup(replicas, cfg.model, ascii_bias)
        # flight steady state: the HTTP warmup covered every shape
        # bucket the schedule exercises, and the prefix-copy grid
        # compiles lazily per reuse length — precompile it like the
        # server warmup does (the flight recorder FOUND this gap: the
        # first soak flagged mid-soak `copy` compiles as steady-state
        # recompiles). From here on any compile the timed soak
        # observes is a real recompile — flagged in the artifact's
        # flight block and attributable to its tail window.
        for r in replicas:
            r.engine.warm_prefix_copies()
            r.engine.mark_flight_warm()

        windows: List[EventWindow] = []
        if cfg.chaos:
            d0 = spec.duration_s * cfg.drain_start_frac
            d1 = spec.duration_s * cfg.drain_end_frac
            kill_at = spec.duration_s * cfg.kill_frac
            # drain one replica we are NOT going to kill, so at least
            # one replica stays routable at every moment
            drain_rid, kill_ix = "r1", 0
            chaos_tasks.append(asyncio.ensure_future(
                _drain_flip(pool, drain_rid, d0, d1)
            ))
            chaos_tasks.append(asyncio.ensure_future(
                _kill_replica(
                    replicas[kill_ix], seed, kill_at,
                    extra_rules=cfg.kill_extra_rules,
                )
            ))
            windows = [
                EventWindow("drain", d0, d1),
                EventWindow(
                    "kill", kill_at,
                    min(spec.duration_s, kill_at + cfg.kill_window_s),
                ),
            ]
        scale_state: dict = {"members": members}
        if cfg.scale_up:
            up_at = spec.duration_s * cfg.scale_up_frac
            chaos_tasks.append(asyncio.ensure_future(_scale_up_replica(
                scale_state, replicas, pool, config, cfg, policy,
                ascii_bias, up_at,
            )))
            # the scored join window: goodput/tails while a cold
            # replica boots, warms, and enters rotation next to live
            # traffic — the acceptance bar is zero client 5xx and no
            # goodput regression vs the baseline soak
            windows.append(EventWindow(
                "scale_up", up_at,
                min(spec.duration_s, up_at + cfg.scale_up_window_s),
            ))

        router_url = f"http://127.0.0.1:{router.port}"
        driver = OpenLoopDriver(
            router_url,
            payload_for=lambda ev: {
                **default_payload(ev, cfg.model),
                "logit_bias": ascii_bias,
            },
            headers_for=lambda ev: {"X-Soak-Tenant": ev.tenant},
            drain_s=cfg.drain_s,
            # fresh per-soak registry: the artifact embeds its render,
            # which must count THIS soak only (back-to-back runs in
            # one process must not leak into each other's artifacts —
            # the same honesty the router-family deltas get)
            registry=new_loadgen_registry(),
        )
        r0 = _snapshot(get_router_registry(), _ROUTER_FAMILIES)
        # flight-recorder baseline: the artifact's flight block deltas
        # compile/post-mortem accounting over the TIMED soak only
        # (warmup compiles are the point of warmup, not a finding)
        from dstack_tpu.obs import flight as obs_flight

        flight_rec = obs_flight.get_recorder()
        f0 = (
            flight_rec.compile_totals() if flight_rec is not None else None
        )
        # monotonic capture count, NOT len(postmortems()): the snapshot
        # buffer saturates at POSTMORTEM_KEEP, which would undercount a
        # stormy soak and zero out back-to-back soaks in one process
        pm0 = (
            flight_rec.postmortems_total() if flight_rec is not None else 0
        )
        # schedule-time anchor for the live SLO transition timeline
        # (the chaos tasks anchored their sleeps moments earlier; the
        # skew is milliseconds against seconds-scale windows)
        soak_t0 = time.monotonic()
        wall_t0 = time.time()  # flight events carry wall-clock stamps
        records = await driver.run(schedule.events)
        router_delta = {
            k: int(v - r0[k])
            for k, v in _snapshot(
                get_router_registry(), _ROUTER_FAMILIES
            ).items()
        }
    finally:
        for t in chaos_tasks:
            t.cancel()
        if probe_task is not None:
            probe_task.cancel()
        await asyncio.gather(
            *chaos_tasks,
            *( [probe_task] if probe_task is not None else [] ),
            return_exceptions=True,
        )
        if session_holder.get("session") is not None:
            await session_holder["session"].close()
        if router_runner is not None:
            await _stop_runner(router_runner.runner)
        for r in replicas:
            if not r.killed:
                try:
                    await r.site.stop()
                except RuntimeError:
                    pass
            await _stop_runner(r.runner)
        # restore whatever fault plan the process came in with
        if prior_rules is not None:
            faults.install_plan(prior_rules)
        elif faults.active():
            faults.clear()

    # trace-based tail attribution: router and replicas all run in this
    # process, so the obs.tracing ring (imported above, where the soak
    # sized it to the schedule) holds the STITCHED trace — router legs
    # + replica phases — for the report to attribute each window's
    # worst requests from
    # flight block: compile/post-mortem deltas over the timed soak +
    # memory watermarks, and the soak-relative compile-event list so
    # the report can attribute tail-amplification windows to compile
    # stalls (a steady-state recompile inside the kill window is a
    # different finding than router retry overhead)
    flight_block = None
    flight_events: list = []
    if flight_rec is not None and f0 is not None:
        f1 = flight_rec.compile_totals()
        mem = flight_rec.memory()
        flight_events = [
            {
                "t": round(e["t"] - wall_t0, 3),
                "fn": e["fn"],
                "key": e.get("key"),
                "seconds": e["seconds"],
                "recompile": e.get("recompile", False),
            }
            for e in flight_rec.compile_events()
            if e["t"] >= wall_t0
        ]
        flight_block = {
            "compiles": {
                fn: int(n - f0["compiles"].get(fn, 0))
                for fn, n in f1["compiles"].items()
                if n - f0["compiles"].get(fn, 0)
            },
            "recompiles": int(
                sum(f1["recompiles"].values())
                - sum(f0["recompiles"].values())
            ),
            "compile_seconds": round(
                sum(f1["seconds"].values()) - sum(f0["seconds"].values()),
                4,
            ),
            "postmortems": flight_rec.postmortems_total() - pm0,
            "peak_memory_bytes": (
                mem.get("peak_bytes_in_use")
                if mem.get("available")
                else None
            ),
            "memory_available": bool(mem.get("available")),
            "events": flight_events,
        }
    analysis = evaluate(
        records,
        {c.name: (c.ttft_slo_ms, c.tpot_slo_ms) for c in spec.classes},
        spec.duration_s,
        windows=windows,
        trace_lookup=obs_tracing.get_trace,
        flight_events=flight_events if flight_block is not None else None,
    )
    # the scale-up replica's TTFST decomposition (obs/boot.py): the
    # per-stage boot timeline from its private recorder, schedule-
    # relative spawn time, and the /health-shaped summary — read next
    # to the `scale_up` entry in the window analysis (goodput/tails
    # around the join). Same backend/note labels as the whole
    # artifact: on CPU fallback these stage durations are NOT TPU boot
    # numbers.
    boot_block = None
    boot_rec = scale_state.get("recorder") if cfg.scale_up else None
    if boot_rec is not None:
        up_engine = scale_state.get("engine")
        boot_block = {
            "replica": f"r{cfg.replicas}",
            "t_spawn": round(scale_state.get("t_spawn", 0.0), 3),
            **boot_rec.health_block(
                warm=bool(up_engine is not None and up_engine.flight_warm)
            ),
            "timeline": boot_rec.timeline(),
            "manifest_variants": (
                len(up_engine.compile_manifest())
                if up_engine is not None else 0
            ),
        }
    info = backend_info()
    result = {
        "metric": (
            f"loadgen_goodput_under_slo[{cfg.model},"
            f"replicas={cfg.replicas}]"
        ),
        "value": analysis["overall"]["goodput_ratio"],
        "unit": "ratio",
        "seed": seed,
        "schedule_digest": schedule.digest(),
        "events": len(schedule.events),
        "duration_s": spec.duration_s,
        "replicas": cfg.replicas,
        "qos": {
            "rps": cfg.qos_rps,
            "burst": cfg.qos_burst,
            "tenant_inflight": cfg.tenant_inflight,
        },
        "chaos": (
            {
                "drain": [w.start for w in windows if w.name == "drain"]
                + [w.end for w in windows if w.name == "drain"],
                "kill_at": next(
                    (w.start for w in windows if w.name == "kill"), None
                ),
            }
            if cfg.chaos
            else None
        ),
        "backend": info["backend"],
        "note": info["note"],
        # engine-side observability over the timed soak (obs/flight.py;
        # same backend label as the artifact — CPU-fallback honesty
        # applies to memory/compile numbers too)
        "flight": flight_block,
        # scale-up boot decomposition (None unless cfg.scale_up): the
        # TTFST baseline for ROADMAP item 4
        "boot": boot_block,
        "slo": (
            {
                "policy": slo_engine.policy.name,
                "windows_s": {
                    k: round(v, 3) for k, v in slo_engine.windows.items()
                },
                # schedule-relative timestamps, matching the report's
                # tail-amplification windows — live and offline views
                # of the same soak line up by construction
                "transitions": [
                    {**tr.to_dict(), "t": round(tr.t - soak_t0, 3)}
                    for tr in slo_engine.transitions
                ],
            }
            if slo_engine is not None
            else None
        ),
        "router": router_delta,
        "spec": spec.to_dict(),
        # the dtpu_loadgen_* families' Prometheus text, embedded so
        # the artifact carries the driver's own raw accounting next to
        # the derived analysis (docs/reference/server.md)
        "loadgen_metrics": driver.metrics.render(),
        **analysis,
    }
    return result


class _Router:
    __slots__ = ("runner", "port")

    def __init__(self, runner, port):
        self.runner = runner
        self.port = port


async def _start_router(pool, session_holder) -> _Router:
    import aiohttp
    from aiohttp import web

    # one shared upstream session, created on the running loop before
    # any request (a lazy per-handler create would race on the first
    # concurrent burst and leak the losers)
    session_holder["session"] = aiohttp.ClientSession()
    app = _router_app(pool, session_holder)
    runner = web.AppRunner(app)
    await runner.setup()
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    site = web.SockSite(runner, sock)
    await site.start()
    return _Router(runner, port)


async def _stop_runner(runner) -> None:
    """Bounded cleanup: a wedged handler must not hang the soak's
    teardown (the report is already computed from driver records)."""
    try:
        await asyncio.wait_for(runner.cleanup(), timeout=5.0)
    except (asyncio.TimeoutError, RuntimeError):
        pass


def run_soak(schedule: EventSchedule, cfg: Optional[SoakConfig] = None) -> dict:
    """Synchronous entry: run one soak → the artifact dict (written to
    ``cfg.output`` when set)."""
    cfg = cfg or SoakConfig()
    result = asyncio.run(_soak_async(schedule, cfg))
    if cfg.output:
        with open(cfg.output, "w") as f:
            json.dump(result, f, indent=1, sort_keys=False)
            f.write("\n")
        logger.warning("soak artifact written to %s", cfg.output)
    return result
