"""Goodput-under-SLO evaluation → the ``SOAK_*.json`` artifact.

The scoring contract (ROADMAP item 5 / DistServe's argument): the
number that matters at scale is **goodput under SLO** — completions
that met their tenant class's TTFT/TPOT targets, per class, as a
fraction of everything that class asked for — not raw throughput. A
449-token/s soak that blew every interactive TTFT target is a failing
soak.

Outcome accounting is deliberately opinionated:

- A **429 with a Retry-After hint is QoS working**, not a failure —
  provided the hints are *honest*: every shed carries one, and within
  a tenant's consecutive run of sheds the hints never grow (the
  monotone contract ``qos.TokenBucket.retry_after`` documents). Sheds
  count against goodput's denominator (the work was asked for and not
  served) but never against ``failures``.
- A **5xx, a truncated stream, or an in-band terminal error event is
  always a failure** — under this harness the router's failover and
  mid-stream resume machinery exist precisely so clients never see
  one, so the chaos acceptance asserts ``failures == 0`` through a
  replica kill.

**Tail amplification** is scored per injected-event window (replica
kill, drain flip): TTFT p95 inside the window over the pre-window
baseline, plus the goodput dip and whether the post-window tail
recovered.

Import-light (stdlib only): unit tests score synthetic record lists
without aiohttp or jax.
"""

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: slack when checking the monotone Retry-After contract: hints are
#: floats derived from a refill schedule read a little later each time
_HINT_SLACK_S = 0.05

_FAILURE_OUTCOMES = (
    "failed_5xx", "failed_connect", "failed_truncated",
    "failed_stream_error", "abandoned",
)


@dataclass
class RequestRecord:
    """One fired event's terminal accounting (driver output)."""

    rid: str
    cls: str
    tenant: str
    t_sched: float  # compiled schedule time (soak-relative seconds)
    t_sent: float  # actual fire time (soak-relative seconds)
    outcome: str  # one of metrics.OUTCOMES
    session: Optional[str] = None
    turn: int = 0
    status: Optional[int] = None
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    tokens: int = 0
    retry_after: Optional[float] = None
    detail: str = ""
    # the router's X-DTPU-Trace response echo: the key that links this
    # record to its distributed trace for tail attribution
    trace_id: Optional[str] = None

    @property
    def lag_s(self) -> float:
        return max(0.0, self.t_sent - self.t_sched)


@dataclass(frozen=True)
class EventWindow:
    """One injected-event interval (soak-relative seconds) the report
    scores tail amplification over."""

    name: str
    start: float
    end: float

    def covers(self, t: float) -> bool:
        return self.start <= t < self.end


def percentile(samples: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over a small sample list (no numpy on
    the report path — same helper contract as serve/bench.py)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, max(0, round(q * (len(s) - 1))))]


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 1)


def _meets_slo(
    r: RequestRecord, ttft_slo_ms: float, tpot_slo_ms: float
) -> bool:
    if r.outcome != "ok" or r.ttft_s is None:
        return False
    if r.ttft_s * 1e3 > ttft_slo_ms:
        return False
    if r.tpot_s is not None and r.tpot_s * 1e3 > tpot_slo_ms:
        return False
    return True


def _shed_honesty(records: Sequence[RequestRecord]) -> dict:
    """Honest-shed accounting: every 429 carries a Retry-After, and
    within one tenant's consecutive shed run the hints never grow."""
    missing: List[str] = []
    grew: List[str] = []
    by_tenant: Dict[str, List[RequestRecord]] = {}
    for r in records:
        by_tenant.setdefault(r.tenant, []).append(r)
    sheds = 0
    for tenant, recs in by_tenant.items():
        recs.sort(key=lambda r: (r.t_sent, r.rid))
        prev_hint: Optional[float] = None
        for r in recs:
            if r.outcome != "shed":
                prev_hint = None  # an admit ends the flood run
                continue
            sheds += 1
            if r.retry_after is None:
                missing.append(r.rid)
                prev_hint = None
                continue
            if (
                prev_hint is not None
                and r.retry_after > prev_hint + _HINT_SLACK_S
            ):
                grew.append(r.rid)
            prev_hint = r.retry_after
    return {
        "sheds": sheds,
        "honest": not missing and not grew,
        "missing_retry_after": missing,
        "hint_grew": grew,
    }


#: TTFT-relevant phases a window's worst requests attribute to (decode
#: happens after the first token and is reported but never dominant)
_TTFT_PHASES = ("qos_queue", "prefill", "router_retry")


def attribute_trace_phases(trace) -> Optional[dict]:
    """One completed trace (the ``obs.tracing`` dict shape) → per-phase
    duration sums and the dominant TTFT phase, or None.

    Phases: ``qos_queue`` (serve.queue spans — admission-queue wait),
    ``prefill`` (serve.prefill), ``decode`` (serve.decode), and
    ``router_retry`` (router.dispatch legs that did NOT complete ok —
    the failover/resume overhead a kill window inflicts). Stdlib-only
    on purpose: the lookup callable is injected, so unit tests attribute
    synthetic trace dicts without aiohttp."""
    if not isinstance(trace, dict):
        return None
    sums = {
        "qos_queue": 0.0, "prefill": 0.0, "decode": 0.0,
        "router_retry": 0.0,
    }
    for s in trace.get("spans", []):
        d = s.get("duration_s") or 0.0
        name = s.get("name")
        if name == "serve.queue":
            sums["qos_queue"] += d
        elif name == "serve.prefill":
            sums["prefill"] += d
        elif name == "serve.decode":
            sums["decode"] += d
        elif name == "router.dispatch" and s.get("status") not in ("ok", None):
            sums["router_retry"] += d
    dominant = None
    if any(sums[k] > 0.0 for k in _TTFT_PHASES):
        dominant = max(_TTFT_PHASES, key=lambda k: sums[k])
    return {
        "phase_ms": {k: round(v * 1e3, 2) for k, v in sums.items()},
        "dominant_phase": dominant,
    }


def _worst_request_phases(
    records: Sequence[RequestRecord], trace_lookup, n: int = 3
) -> list:
    """The window's ``n`` worst completed requests by TTFT, each
    attributed to its dominant span phase via ``trace_lookup(trace_id)
    → trace dict or None`` (the soak passes ``obs.tracing.get_trace``
    — router and replicas share one in-process ring there)."""
    worst = sorted(
        (r for r in records if r.outcome == "ok" and r.ttft_s is not None),
        key=lambda r: r.ttft_s,
        reverse=True,
    )[: max(0, int(n))]
    out = []
    for r in worst:
        entry = {
            "rid": r.rid,
            "ttft_ms": _ms(r.ttft_s),
            "trace_id": r.trace_id,
        }
        attributed = (
            attribute_trace_phases(trace_lookup(r.trace_id))
            if r.trace_id
            else None
        )
        if attributed is not None:
            entry.update(attributed)
        else:
            # honest gap: the trace rotated out of the bounded ring (or
            # tracing was off) — the record still lists, unattributed
            entry["dominant_phase"] = None
        out.append(entry)
    return out


def _bucket_stats(
    records: Sequence[RequestRecord],
    slos: Dict[str, Tuple[float, float]],
    span_s: float,
) -> dict:
    """Outcome/latency/goodput stats over one record subset."""
    ok = [r for r in records if r.outcome == "ok"]
    ttfts = [r.ttft_s for r in ok if r.ttft_s is not None]
    tpots = [r.tpot_s for r in ok if r.tpot_s is not None]
    met = sum(
        1 for r in records if _meets_slo(r, *slos.get(r.cls, (1e12, 1e12)))
    )
    outcomes: Dict[str, int] = {}
    for r in records:
        outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
    n = len(records)
    return {
        "requests": n,
        "outcomes": outcomes,
        "completed": len(ok),
        "slo_met": met,
        "goodput_ratio": round(met / n, 4) if n else None,
        "goodput_rps": round(met / span_s, 3) if span_s > 0 else None,
        "ttft_ms_p50": _ms(percentile(ttfts, 0.5)) if ttfts else None,
        "ttft_ms_p95": _ms(percentile(ttfts, 0.95)) if ttfts else None,
        "ttft_ms_p99": _ms(percentile(ttfts, 0.99)) if ttfts else None,
        "tpot_ms_p50": _ms(percentile(tpots, 0.5)) if tpots else None,
        "tpot_ms_p95": _ms(percentile(tpots, 0.95)) if tpots else None,
    }


def _window_compile_stalls(
    flight_events: Sequence[dict], w: EventWindow
) -> dict:
    """Compile activity inside one window, from the flight recorder's
    soak-relative event list (``{"t": rel_s, "fn", "seconds",
    "recompile"}``): a tail-amplification window whose worst requests
    line up with compile seconds is a compile stall, not a routing or
    queueing problem."""
    hits = [e for e in flight_events if w.covers(float(e.get("t", -1.0)))]
    return {
        "events": len(hits),
        "recompiles": sum(1 for e in hits if e.get("recompile")),
        "seconds": round(
            sum(float(e.get("seconds", 0.0)) for e in hits), 4
        ),
        "fns": sorted({e.get("fn") for e in hits if e.get("fn")}),
    }


def evaluate(
    records: Sequence[RequestRecord],
    class_slos: Dict[str, Tuple[float, float]],
    duration_s: float,
    windows: Sequence[EventWindow] = (),
    trace_lookup=None,
    flight_events: Optional[Sequence[dict]] = None,
) -> dict:
    """Score one soak run → the report's analysis block.

    ``class_slos`` maps class name → (ttft_slo_ms, tpot_slo_ms);
    ``windows`` are the injected-event intervals (kill, drain) whose
    tail amplification and recovery get scored against the pre-window
    baseline. ``trace_lookup`` (``trace_id → obs.tracing trace dict or
    None``, optional) attributes each window's worst requests to their
    dominant span phase — the "WHY did the kill window amplify TTFT
    2×" block of the artifact. ``flight_events`` (optional, from the
    engine flight recorder, timestamps already soak-relative) adds a
    ``compile_stalls`` block per window so a tail spike caused by an
    XLA compile — a steady-state recompile especially — is
    attributable as such."""
    records = list(records)
    per_class: Dict[str, dict] = {}
    for name, slos in sorted(class_slos.items()):
        recs = [r for r in records if r.cls == name]
        stats = _bucket_stats(recs, {name: slos}, duration_s)
        stats["ttft_slo_ms"] = slos[0]
        stats["tpot_slo_ms"] = slos[1]
        stats["sheds"] = _shed_honesty(recs)
        per_class[name] = stats
    overall = _bucket_stats(records, class_slos, duration_s)
    overall["sheds"] = _shed_honesty(records)
    failures = sum(
        overall["outcomes"].get(o, 0) for o in _FAILURE_OUTCOMES
    )
    client_5xx = overall["outcomes"].get("failed_5xx", 0)

    lags = [r.lag_s for r in records]
    open_loop = {
        "sched_lag_ms_p95": _ms(percentile(lags, 0.95)) if lags else None,
        "sched_lag_ms_max": _ms(max(lags)) if lags else None,
    }

    window_blocks: Dict[str, dict] = {}
    baseline = tail = None
    if windows:
        first_start = min(w.start for w in windows)
        last_end = max(w.end for w in windows)
        base_recs = [r for r in records if r.t_sched < first_start]
        tail_recs = [r for r in records if r.t_sched >= last_end]
        baseline = _bucket_stats(
            base_recs, class_slos, max(first_start, 1e-9)
        )
        tail = _bucket_stats(
            tail_recs, class_slos, max(duration_s - last_end, 1e-9)
        )
        for w in windows:
            in_w = [r for r in records if w.covers(r.t_sched)]
            blk = _bucket_stats(in_w, class_slos, max(w.end - w.start, 1e-9))
            blk["start"] = w.start
            blk["end"] = w.end
            b95, w95 = baseline["ttft_ms_p95"], blk["ttft_ms_p95"]
            blk["ttft_p95_amplification"] = (
                round(w95 / b95, 2) if b95 and w95 else None
            )
            if trace_lookup is not None:
                blk["worst_requests"] = _worst_request_phases(
                    in_w, trace_lookup
                )
            if flight_events is not None:
                blk["compile_stalls"] = _window_compile_stalls(
                    flight_events, w
                )
            window_blocks[w.name] = blk
        bg, tg = baseline["goodput_ratio"], tail["goodput_ratio"]
        # None (not False): an empty tail or baseline proves nothing —
        # e.g. a kill window clamped to the soak end leaves no tail
        recovered = (
            None
            if bg is None or tg is None
            else tg >= 0.7 * bg
        )
        window_blocks["_recovery"] = {
            "baseline_goodput_ratio": bg,
            "tail_goodput_ratio": tg,
            "recovered": recovered,
        }

    return {
        "overall": overall,
        "classes": per_class,
        "failures": failures,
        "client_5xx": client_5xx,
        "open_loop": open_loop,
        "windows": window_blocks,
        "baseline": baseline,
        "tail": tail,
    }
