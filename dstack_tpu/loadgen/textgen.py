"""Seeded workload text/prompt generators — ONE implementation.

Every seeded workload in the repo draws its text from here:
``serve/bench.py`` (``--sessions`` conversations, ``--arrival-burst``
token prompts) and the loadgen schedule compiler both call these, so a
"realistic prompt" means the same thing in a bench line and a soak
report, and a generator fix never forks the two.

Generators are rng-duck-typed: they accept anything exposing numpy's
``Generator.integers(lo, hi, n)`` — a real ``numpy.random.Generator``
(the bench path) or the stdlib-backed :class:`WordRNG` adapter (the
loadgen schedule path, which must stay importable without numpy).
Given the same rng state the outputs are identical either way, so the
module itself imports nothing but the stdlib.
"""

import random
from typing import List, Sequence, Tuple

_LETTERS = "abcdefghijklmnopqrstuvwxyz"


class WordRNG:
    """Stdlib adapter exposing the one rng method the generators use
    (``integers(lo, hi, n)``), so the schedule compiler stays a pure
    function of its ``random.Random`` streams without importing numpy."""

    __slots__ = ("_r",)

    def __init__(self, rng: random.Random):
        self._r = rng

    def integers(self, lo: int, hi: int, n: int) -> List[int]:
        # half-open [lo, hi) like numpy.Generator.integers
        return [self._r.randrange(lo, hi) for _ in range(n)]


def session_text(rng, n_chars: int) -> str:
    """Seeded pseudo-prose: ~5-char lowercase words until ``n_chars``.
    Deterministic in the rng, so two compilations of the same workload
    replay the exact same conversations."""
    words = []
    total = 0
    while total < n_chars:
        w = "".join(_LETTERS[int(i)] for i in rng.integers(0, 26, 5))
        words.append(w)
        total += len(w) + 1
    return " ".join(words)


def conversation_texts(
    rng, sessions: int, turns: int, turn_chars: int
) -> List[List[str]]:
    """Seeded user-turn texts for ``sessions`` multi-turn chats —
    the ``serve/bench.py --sessions`` workload and the loadgen chat
    classes share this construction (rng consumption order included,
    so a given rng state always yields the same conversations)."""
    return [
        [session_text(rng, turn_chars) for _ in range(turns)]
        for _ in range(sessions)
    ]


def token_prompts(
    rng, vocab_size: int, count: int, length: int
) -> List[List[int]]:
    """``count`` random token-id prompts of ``length`` drawn from
    ``[1, vocab_size)`` — the bench's burst/throughput workload."""
    return [
        [int(t) for t in rng.integers(1, vocab_size, length)]
        for _ in range(count)
    ]


def repetitive_prompts(
    rng, vocab_size: int, count: int, length: int, phrase_len: int = 16
) -> List[List[int]]:
    """``count`` copies of a tiled ``phrase_len``-token phrase —
    the RAG/summarization-like repetition where prompt-lookup
    speculation pays off (``serve/bench.py --repetitive``)."""
    phrase = [int(t) for t in rng.integers(1, vocab_size, phrase_len)]
    reps = length // phrase_len + 1
    return [(phrase * reps)[:length] for _ in range(count)]


def chars_in(rng, bounds: Sequence[int]) -> int:
    """One draw from an inclusive [lo, hi] length range (lo == hi is a
    constant). Shared by the schedule compiler's prompt/turn sizing."""
    lo, hi = int(bounds[0]), int(bounds[1])
    if hi <= lo:
        return lo
    return int(rng.integers(lo, hi + 1, 1)[0])


def bounds_pair(value, default: Tuple[int, int]) -> Tuple[int, int]:
    """Normalize a spec length field: an int means a constant, a
    two-item list an inclusive range."""
    if value is None:
        return default
    if isinstance(value, (int, float)):
        v = int(value)
        return (v, v)
    lo, hi = int(value[0]), int(value[1])
    return (min(lo, hi), max(lo, hi))
