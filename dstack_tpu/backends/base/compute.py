"""Backend ``Compute`` interface.

Parity: reference core/backends/base/compute.py:49-133 (``Compute`` ABC)
and :136-335 (capability mixins). TPU-first: ``create_instance`` may
provision a whole multi-host pod slice; provisioning data then carries
per-worker host metadata (``JobProvisioningData.hosts``).
"""

import abc
from typing import Optional

from dstack_tpu.core.models.instances import (
    InstanceConfiguration,
    InstanceOfferWithAvailability,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.core.models.volumes import (
    Volume,
    VolumeAttachmentData,
    VolumeProvisioningData,
)


class Compute(abc.ABC):
    """The per-backend provisioning driver."""

    @abc.abstractmethod
    async def get_offers(
        self, requirements: Requirements
    ) -> list[InstanceOfferWithAvailability]:
        ...

    @abc.abstractmethod
    async def terminate_instance(
        self, instance_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        ...

    async def update_provisioning_data(
        self, provisioning_data: JobProvisioningData
    ) -> JobProvisioningData:
        """Poll the cloud for IPs/hostnames of a provisioning instance;
        returns updated data (reference compute.py:update_provisioning_data)."""
        return provisioning_data


class ComputeWithCreateInstanceSupport(abc.ABC):
    """Backends that can provision instances independent of a job
    (fleets `nodes: N`, pool reuse)."""

    @abc.abstractmethod
    async def create_instance(
        self,
        instance_offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
    ) -> JobProvisioningData:
        ...


class ComputeWithMultinodeSupport:
    """Marker: offers may be multi-host TPU slices / cluster placement.

    The reference explicitly excludes multi-host TPUs
    (reference gcp/compute.py:699-726); here they are the headline
    feature — a slice provisions atomically, all workers or nothing.
    """


class ComputeWithReservationSupport:
    """Marker: supports capacity reservations (GCP future reservations)."""


class ComputeWithPlacementGroupSupport(abc.ABC):
    @abc.abstractmethod
    async def create_placement_group(self, name: str, region: str) -> str:
        """Returns backend_data."""

    @abc.abstractmethod
    async def delete_placement_group(self, name: str, region: str, backend_data: str) -> None:
        ...


class ComputeWithGatewaySupport(abc.ABC):
    @abc.abstractmethod
    async def create_gateway(self, name: str, region: str) -> dict:
        """Provision a gateway VM; returns provisioning data
        ``{instance_id, ip_address, region, agent_port, agent_token?}``."""

    @abc.abstractmethod
    async def terminate_gateway(self, instance_id: str, region: str) -> None:
        ...

    async def update_gateway_provisioning_data(self, pd: dict) -> dict:
        """Poll the cloud for the gateway VM's IP when it wasn't
        available at create time; returns updated provisioning data."""
        return pd


class ComputeWithVolumeSupport(abc.ABC):
    @abc.abstractmethod
    async def create_volume(self, volume: Volume) -> VolumeProvisioningData:
        ...

    @abc.abstractmethod
    async def delete_volume(self, volume: Volume) -> None:
        ...

    async def attach_volume(self, volume: Volume, instance_id: str) -> VolumeAttachmentData:
        raise NotImplementedError

    async def detach_volume(self, volume: Volume, instance_id: str) -> None:
        raise NotImplementedError

    async def register_volume(self, volume: Volume) -> VolumeProvisioningData:
        raise NotImplementedError


def get_backend_capabilities(compute_cls: type) -> dict[str, bool]:
    """Capability matrix from mixin subclassing
    (reference core/backends/__init__.py:31-60)."""
    return {
        "create_instance": issubclass(compute_cls, ComputeWithCreateInstanceSupport),
        "multinode": issubclass(compute_cls, ComputeWithMultinodeSupport),
        "reservations": issubclass(compute_cls, ComputeWithReservationSupport),
        "placement_groups": issubclass(compute_cls, ComputeWithPlacementGroupSupport),
        "gateways": issubclass(compute_cls, ComputeWithGatewaySupport),
        "volumes": issubclass(compute_cls, ComputeWithVolumeSupport),
    }
