from dstack_tpu.backends.local.compute import LocalCompute  # noqa: F401
