"""Local backend: this machine as a single-instance "cloud".

Parity: reference core/backends/local (dev backend offering a fake
instance; server talks to a locally-started shim without SSH,
runner/ssh.py:64-66). Here the local backend actually *provisions*: it
spawns a ``tpu-shim-py`` subprocess per instance (process runtime, no
Docker needed), so an end-to-end run works on one machine — the test
strategy's "distributed without a cluster" backbone (SURVEY.md §4).
"""

import asyncio
import socket
import sys
from pathlib import Path
from typing import Optional

import psutil

from dstack_tpu.backends.base.compute import (
    Compute,
    ComputeWithCreateInstanceSupport,
    ComputeWithGatewaySupport,
    ComputeWithMultinodeSupport,
)
from dstack_tpu.core.models.backends import BackendType
from dstack_tpu.core.models.instances import (
    HostMetadata,
    InstanceAvailability,
    InstanceConfiguration,
    InstanceOfferWithAvailability,
    InstanceType,
    Resources,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.utils.logging import get_logger

logger = get_logger("backends.local")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalCompute(
    Compute,
    ComputeWithCreateInstanceSupport,
    ComputeWithMultinodeSupport,
    ComputeWithGatewaySupport,
):
    """Each "instance" is a local shim subprocess with a process runtime."""

    def __init__(self, base_dir: Optional[Path] = None):
        import atexit

        self.base_dir = base_dir or Path.home() / ".dtpu" / "local-backend"
        self._procs: dict[str, asyncio.subprocess.Process] = {}
        # shim subprocesses run in their own session; reap them when this
        # process exits so tests/server shutdown don't leak agents
        atexit.register(self._kill_all)

    def _kill_all(self) -> None:
        import os
        import signal

        for proc in self._procs.values():
            if proc.returncode is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

    async def get_offers(
        self, requirements: Requirements
    ) -> list[InstanceOfferWithAvailability]:
        res = requirements.resources
        tpu_info = None
        if res.tpu is not None:
            # Local host has no schedulable TPU slices unless detected —
            # or faked via DTPU_LOCAL_FAKE_TPU=v5e-8 for e2e tests of
            # the multislice rendezvous wiring (each local "slice" is a
            # shim subprocess; the job runs on CPU).
            import os

            from dstack_tpu.agent.python.shim import detect_tpu

            fake = os.environ.get("DTPU_LOCAL_FAKE_TPU")
            if fake:
                from dstack_tpu.core.catalog.tpu import GENERATIONS, TPU_SLICES
                from dstack_tpu.core.models.instances import TPUInfo
                from dstack_tpu.core.models.resources import (
                    normalize_tpu_version,
                )

                version, _, chips_s = fake.rpartition("-")
                try:
                    version = normalize_tpu_version(version)
                    chips = int(chips_s)
                except (ValueError, KeyError):
                    logger.warning(
                        "DTPU_LOCAL_FAKE_TPU=%r is not <generation>-<chips> "
                        "(e.g. v5e-8); offering no TPU", fake,
                    )
                    return []
                shape = next(
                    (
                        s for s in TPU_SLICES
                        if s.version == version and s.chips == chips
                    ),
                    None,
                )
                if shape is None:
                    logger.warning(
                        "DTPU_LOCAL_FAKE_TPU=%r matches no catalog slice "
                        "shape; offering no TPU", fake,
                    )
                    return []
                tpu_info = TPUInfo(
                    version=shape.version,
                    chips=shape.chips,
                    topology=shape.topology,
                    hosts=shape.hosts,
                    chips_per_host=GENERATIONS[shape.version].chips_per_host,
                )
            elif detect_tpu() is None:
                return []
        # Dev backend: offer the host as-is without cpu/mem minimum
        # filtering (the reference local backend offers its fake instance
        # unconditionally too) — dev containers often report 1 vCPU.
        cpus = psutil.cpu_count() or 1
        mem_mib = psutil.virtual_memory().total // (1024 * 1024)
        offer = InstanceOfferWithAvailability(
            backend=BackendType.LOCAL,
            instance=InstanceType(
                name="local",
                resources=Resources(
                    cpus=cpus, memory_mib=mem_mib, spot=False,
                    disk_size_mib=51200, tpu=tpu_info,
                ),
            ),
            region="local",
            price=0.0,
            availability=InstanceAvailability.AVAILABLE,
        )
        return [offer]

    @staticmethod
    def _native_agent_paths() -> Optional[tuple[Path, Path]]:
        """(tpu-shim, tpu-runner) native binaries when built and enabled
        via DTPU_NATIVE_AGENT=1."""
        import os

        if os.getenv("DTPU_NATIVE_AGENT") != "1":
            return None
        root = Path(__file__).resolve().parents[3]
        shim = root / "build" / "tpu-shim"
        runner = root / "build" / "tpu-runner"
        if shim.exists() and runner.exists():
            return shim, runner
        return None

    async def create_instance(
        self,
        instance_offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
    ) -> JobProvisioningData:
        shim_port = _free_port()
        inst_dir = self.base_dir / instance_config.instance_name
        inst_dir.mkdir(parents=True, exist_ok=True)
        native = self._native_agent_paths()
        if native is not None:
            shim_bin, runner_bin = native
            cmd = [
                str(shim_bin),
                "--port", str(shim_port),
                "--base-dir", str(inst_dir),
                "--runtime", "process",
                "--runner-bin", str(runner_bin),
            ]
        else:
            cmd = [
                sys.executable,
                "-m",
                "dstack_tpu.agent.python.shim_main",
                "--port", str(shim_port),
                "--base-dir", str(inst_dir),
                "--runtime", "process",
            ]
        proc = await asyncio.create_subprocess_exec(
            *cmd,
            start_new_session=True,
        )
        instance_id = f"local-{shim_port}"
        self._procs[instance_id] = proc
        logger.info(
            "local instance %s: shim pid=%d port=%d", instance_id, proc.pid, shim_port
        )
        return JobProvisioningData(
            backend=BackendType.LOCAL,
            instance_type=instance_offer.instance,
            instance_id=instance_id,
            hostname="127.0.0.1",
            internal_ip="127.0.0.1",
            region=instance_offer.region,
            price=0.0,
            username="local",
            ssh_port=0,
            dockerized=True,
            hosts=[
                HostMetadata(
                    worker_id=0,
                    internal_ip="127.0.0.1",
                    external_ip="127.0.0.1",
                    shim_port=shim_port,
                )
            ],
        )

    async def terminate_instance(
        self, instance_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        proc = self._procs.pop(instance_id, None)
        if proc is not None and proc.returncode is None:
            import os
            import signal

            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass

    # ---- gateways: a local tpu-gateway agent subprocess ----

    async def create_gateway(self, name: str, region: str) -> dict:
        port = _free_port()
        gw_dir = self.base_dir / f"gateway-{name}"
        gw_dir.mkdir(parents=True, exist_ok=True)
        proc = await asyncio.create_subprocess_exec(
            sys.executable,
            "-m",
            "dstack_tpu.gateway.app",
            "--host", "127.0.0.1",
            "--port", str(port),
            "--state-file", str(gw_dir / "state.json"),
            start_new_session=True,
        )
        instance_id = f"local-gw-{port}"
        self._procs[instance_id] = proc
        logger.info("local gateway %s: pid=%d port=%d", name, proc.pid, port)
        return {
            "instance_id": instance_id,
            "ip_address": "127.0.0.1",
            "region": region,
            "agent_port": port,
        }

    async def terminate_gateway(self, instance_id: str, region: str) -> None:
        await self.terminate_instance(instance_id, region)
