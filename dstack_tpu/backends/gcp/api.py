"""Thin async REST client for the GCP TPU v2 API.

The reference uses the ``google-cloud-tpu`` SDK (reference
gcp/compute.py:199-254 ``tpu_v2.CreateNodeRequest``); this image has no
SDK, so the framework speaks ``https://tpu.googleapis.com/v2`` directly.
The transport is injectable — tests drive the full backend against a
fake transport, real deployments authenticate via google.auth
(service-account JSON or metadata server).
"""

import asyncio
import json
import os
from typing import Any, Optional

import aiohttp

from dstack_tpu import faults
from dstack_tpu.core.errors import (
    BackendAuthError,
    BackendError,
    BackendRequestError,
)
from dstack_tpu.utils.logging import get_logger
from dstack_tpu.utils.retry import (
    Deadline,
    RetryPolicy,
    default_should_retry,
    retry_async,
)

logger = get_logger("backends.gcp.api")

TPU_API = "https://tpu.googleapis.com/v2"

# Transient-failure budget for one logical API call: 429s, 5xx, and
# connect errors retry with jittered exponential backoff (Retry-After
# respected); 4xx and auth errors never retry. Node/disk creation is
# safe to retry: GCP keys creations on the caller-supplied id, so a
# replayed create answers 409 (not retryable, surfaced).
GCP_RETRY_ATTEMPTS = int(os.getenv("DTPU_GCP_RETRY_ATTEMPTS", "4"))
GCP_RETRY_DEADLINE = float(os.getenv("DTPU_GCP_RETRY_DEADLINE", "120"))

_RETRY_POLICY = RetryPolicy(
    max_attempts=GCP_RETRY_ATTEMPTS, base_delay=0.5, max_delay=15.0
)


class Transport:
    """Pluggable HTTP layer (tests install a fake). Pools one client
    session and refreshes OAuth credentials on expiry."""

    def __init__(self, credentials: Any = None):
        self._credentials = credentials
        self._session: Optional[aiohttp.ClientSession] = None

    def _get_token(self) -> str:
        try:
            if self._credentials is None:
                import google.auth

                creds, _ = google.auth.default(
                    scopes=["https://www.googleapis.com/auth/cloud-platform"]
                )
                self._credentials = creds
            creds = self._credentials
            # refresh expired/initial tokens (long-running server: tokens
            # expire hourly)
            if not getattr(creds, "valid", False) and hasattr(creds, "refresh"):
                import google.auth.transport.requests

                creds.refresh(google.auth.transport.requests.Request())
        except Exception as e:
            raise BackendAuthError(f"GCP auth failed: {e}") from e
        if hasattr(self._credentials, "token"):
            return self._credentials.token
        raise BackendAuthError("no usable GCP credentials")

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60),
                connector=aiohttp.TCPConnector(limit=32, keepalive_timeout=30),
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def request(
        self,
        method: str,
        url: str,
        json_body: Optional[dict] = None,
        params: Optional[dict] = None,
    ) -> dict:
        """One logical API call: transient failures (429/5xx/connect
        errors/timeouts) retry per :data:`_RETRY_POLICY` under an
        overall deadline; auth errors and 4xx surface immediately."""
        deadline = Deadline(GCP_RETRY_DEADLINE)

        async def _attempt() -> dict:
            await faults.afire("gcp.api.request", method=method, url=url)
            loop = asyncio.get_running_loop()
            token = await loop.run_in_executor(None, self._get_token)
            session = self._get_session()
            async with session.request(
                method,
                url,
                json=json_body,
                params=params,
                headers={"Authorization": f"Bearer {token}"},
            ) as resp:
                text = await resp.text()
                if resp.status >= 400:
                    raise BackendRequestError(
                        f"GCP API {method} {url}: {resp.status} {text[:400]}",
                        status=resp.status,
                        retry_after=resp.headers.get("Retry-After"),
                    )
                result = json.loads(text) if text else {}
                return faults.mutate(
                    "gcp.api.request", result, method=method, url=url
                )

        def _transient(exc: BaseException) -> bool:
            # the shared classifier (429/5xx via the status attr,
            # connect errors, timeouts) with one backend-specific
            # carve-out: auth failures never retry
            if isinstance(exc, BackendAuthError):
                return False
            return default_should_retry(exc)

        return await retry_async(
            _attempt,
            site="gcp.api",
            policy=_RETRY_POLICY,
            should_retry=_transient,
            deadline=deadline,
        )


class TPUNodesAPI:
    """TPU node + queued-resource lifecycle."""

    def __init__(self, project: str, transport: Optional[Transport] = None):
        self.project = project
        self.transport = transport or Transport()

    def _zone_parent(self, zone: str) -> str:
        return f"projects/{self.project}/locations/{zone}"

    async def create_node(
        self,
        zone: str,
        node_id: str,
        accelerator_type: str,
        runtime_version: str,
        startup_script: str,
        spot: bool = False,
        network: str = "default",
        data_disks: Optional[list[dict]] = None,
        labels: Optional[dict[str, str]] = None,
        reservation: Optional[str] = None,
    ) -> dict:
        body: dict = {
            "acceleratorType": accelerator_type,
            "runtimeVersion": runtime_version,
            "networkConfig": {"network": network, "enableExternalIps": True},
            "metadata": {"startup-script": startup_script},
            "labels": labels or {},
            "dataDisks": data_disks or [],
        }
        if spot:
            body["schedulingConfig"] = {"preemptible": True, "spot": True}
        if reservation:
            body["schedulingConfig"] = {
                **body.get("schedulingConfig", {}),
                "reserved": True,
            }
        return await self.transport.request(
            "POST",
            f"{TPU_API}/{self._zone_parent(zone)}/nodes",
            json_body=body,
            params={"nodeId": node_id},
        )

    async def create_queued_resource(
        self,
        zone: str,
        resource_id: str,
        node_id: str,
        accelerator_type: str,
        runtime_version: str,
        startup_script: str,
        spot: bool = False,
        valid_for_seconds: int = 600,
        network: str = "default",
        labels: Optional[dict[str, str]] = None,
        reservation: Optional[str] = None,
        data_disks: Optional[list[dict]] = None,
    ) -> dict:
        """QueuedResources: the all-workers-or-nothing path for big pod
        slices (v5p/v6e) — parity gap the reference punts on."""
        body: dict = {
            "tpu": {
                "nodeSpec": [
                    {
                        "parent": self._zone_parent(zone),
                        "nodeId": node_id,
                        "node": {
                            "acceleratorType": accelerator_type,
                            "runtimeVersion": runtime_version,
                            "metadata": {"startup-script": startup_script},
                            "networkConfig": {
                                "network": network,
                                "enableExternalIps": True,
                            },
                            "labels": labels or {},
                            "dataDisks": data_disks or [],
                        },
                    }
                ]
            },
            "queueingPolicy": {"validUntilDuration": f"{valid_for_seconds}s"},
        }
        if spot:
            body["spot"] = {}
        if reservation:
            body["reservationName"] = reservation
        return await self.transport.request(
            "POST",
            f"{TPU_API}/{self._zone_parent(zone)}/queuedResources",
            json_body=body,
            params={"queuedResourceId": resource_id},
        )

    async def get_node(self, zone: str, node_id: str) -> dict:
        return await self.transport.request(
            "GET", f"{TPU_API}/{self._zone_parent(zone)}/nodes/{node_id}"
        )

    async def delete_node(self, zone: str, node_id: str) -> dict:
        return await self.transport.request(
            "DELETE", f"{TPU_API}/{self._zone_parent(zone)}/nodes/{node_id}"
        )

    async def delete_queued_resource(self, zone: str, resource_id: str) -> dict:
        return await self.transport.request(
            "DELETE",
            f"{TPU_API}/{self._zone_parent(zone)}/queuedResources/{resource_id}",
            params={"force": "true"},
        )

    async def update_node_disks(self, zone: str, node_id: str, data_disks: list[dict]) -> dict:
        """Volume attach/detach via UpdateNode(dataDisks)
        (reference gcp/compute.py:578-676)."""
        return await self.transport.request(
            "PATCH",
            f"{TPU_API}/{self._zone_parent(zone)}/nodes/{node_id}",
            json_body={"dataDisks": data_disks},
            params={"updateMask": "dataDisks"},
        )


GCE_API = "https://compute.googleapis.com/compute/v1"


class GCEInstancesAPI:
    """Plain GCE VM lifecycle — used for gateway VMs (reference
    provisions the gateway via the backend's generic VM path,
    base/compute.py:684-692 + gcp compute)."""

    def __init__(self, project: str, transport: Optional[Transport] = None):
        self.project = project
        self.transport = transport or Transport()

    def _zone_url(self, zone: str) -> str:
        return f"{GCE_API}/projects/{self.project}/zones/{zone}"

    async def create_instance(
        self,
        zone: str,
        name: str,
        machine_type: str = "e2-small",
        startup_script: str = "",
        tags: Optional[list[str]] = None,
        public_ip: bool = True,
    ) -> dict:
        body = {
            "name": name,
            "machineType": f"zones/{zone}/machineTypes/{machine_type}",
            "disks": [
                {
                    "boot": True,
                    "autoDelete": True,
                    "initializeParams": {
                        "sourceImage": (
                            "projects/ubuntu-os-cloud/global/images/family/"
                            "ubuntu-2204-lts"
                        ),
                        "diskSizeGb": "30",
                    },
                }
            ],
            "networkInterfaces": [
                {
                    "network": "global/networks/default",
                    **(
                        {"accessConfigs": [{"type": "ONE_TO_ONE_NAT"}]}
                        if public_ip
                        else {}
                    ),
                }
            ],
            "metadata": {
                "items": [{"key": "startup-script", "value": startup_script}]
            },
            "tags": {"items": tags or ["tpu-gateway"]},
        }
        return await self.transport.request(
            "POST", f"{self._zone_url(zone)}/instances", json_body=body
        )

    async def get_instance(self, zone: str, name: str) -> dict:
        return await self.transport.request(
            "GET", f"{self._zone_url(zone)}/instances/{name}"
        )

    # ---- persistent disks (TPU data disks ride these) ----

    async def create_disk(
        self, zone: str, name: str, size_gb: int, disk_type: str = "pd-balanced"
    ) -> dict:
        body = {
            "name": name,
            "sizeGb": str(size_gb),
            "type": f"zones/{zone}/diskTypes/{disk_type}",
        }
        return await self.transport.request(
            "POST", f"{self._zone_url(zone)}/disks", json_body=body
        )

    async def get_disk(self, zone: str, name: str) -> dict:
        return await self.transport.request(
            "GET", f"{self._zone_url(zone)}/disks/{name}"
        )

    async def delete_disk(self, zone: str, name: str) -> dict:
        return await self.transport.request(
            "DELETE", f"{self._zone_url(zone)}/disks/{name}"
        )

    async def delete_instance(self, zone: str, name: str) -> dict:
        return await self.transport.request(
            "DELETE", f"{self._zone_url(zone)}/instances/{name}"
        )

    async def ensure_firewall_rule(
        self, name: str, target_tag: str, ports: list[str]
    ) -> None:
        """Idempotently open ingress ports for instances with a tag
        (the gateway agent port is not covered by GCP's default
        http-server/https-server rules)."""
        body = {
            "name": name,
            "network": "global/networks/default",
            "direction": "INGRESS",
            "allowed": [{"IPProtocol": "tcp", "ports": ports}],
            "sourceRanges": ["0.0.0.0/0"],
            "targetTags": [target_tag],
        }
        try:
            await self.transport.request(
                "POST",
                f"{GCE_API}/projects/{self.project}/global/firewalls",
                json_body=body,
            )
        except BackendError as e:
            if (
                getattr(e, "status", None) != 409
                and "409" not in str(e)
                and "alreadyExists" not in str(e)
            ):
                raise


def runtime_version_for(tpu_version: str) -> str:
    """TPU runtime image matrix (reference gcp/compute.py:775-781)."""
    return {
        "v2": "tpu-ubuntu2204-base",
        "v3": "tpu-ubuntu2204-base",
        "v4": "tpu-ubuntu2204-base",
        "v5e": "v2-alpha-tpuv5-lite",
        "v5p": "v2-alpha-tpuv5",
        "v6e": "v2-alpha-tpuv6e",
    }.get(tpu_version, "tpu-ubuntu2204-base")


# zone table: region -> zone with TPU capacity (catalog data)
TPU_ZONES = {
    "us-central1": "us-central1-a",
    "us-central2": "us-central2-b",
    "us-east1": "us-east1-d",
    "us-east5": "us-east5-a",
    "us-west4": "us-west4-a",
    "europe-west4": "europe-west4-a",
    "asia-east1": "asia-east1-c",
    "asia-southeast1": "asia-southeast1-b",
    "asia-northeast1": "asia-northeast1-b",
}
