"""GCP TPU backend: pod slices as first-class instances.

Parity+: reference gcp/compute.py supports single-host TPUs only
(:699-726, ``_is_single_host_tpu:788-805``); here **multi-host slices
are the point** — one ``create_node`` provisions the whole slice, every
worker host runs a shim (installed by the startup script), and
``update_provisioning_data`` polls ``networkEndpoints`` until all
workers have IPs (all-or-nothing).
"""

import asyncio
import json
import shlex
from typing import Optional

from dstack_tpu.backends.base.compute import (
    Compute,
    ComputeWithCreateInstanceSupport,
    ComputeWithGatewaySupport,
    ComputeWithMultinodeSupport,
    ComputeWithReservationSupport,
    ComputeWithVolumeSupport,
)
from dstack_tpu.backends.gcp.api import (
    TPU_ZONES,
    GCEInstancesAPI,
    TPUNodesAPI,
    Transport,
    runtime_version_for,
)
from dstack_tpu.core.catalog import query_slices
from dstack_tpu.core.errors import BackendError, ComputeError
from dstack_tpu.core.models.backends import BackendType
from dstack_tpu.core.models.instances import (
    HostMetadata,
    InstanceAvailability,
    InstanceConfiguration,
    InstanceOfferWithAvailability,
    InstanceType,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.core.models.volumes import (
    Volume,
    VolumeAttachmentData,
    VolumeProvisioningData,
)
from dstack_tpu.utils.logging import get_logger
from dstack_tpu.version import __version__

logger = get_logger("backends.gcp")

SHIM_PORT = 10998


def get_shim_startup_script(authorized_keys: list[str], tpu_generation: str) -> str:
    """Startup script installing + launching tpu-shim on every worker.

    Parity: reference base/compute.py:443-531 (``get_user_data`` /
    ``get_shim_commands`` with ``--pjrt-device``) +
    gcp/compute.py:757-763 (``_get_tpu_startup_script``).
    """
    keys = "\n".join(authorized_keys)
    return f"""#!/bin/bash
set -e
mkdir -p /root/.ssh /root/.dtpu
cat >> /root/.ssh/authorized_keys <<'EOF'
{keys}
EOF
export DTPU_TPU_GENERATION={shlex.quote(tpu_generation)}
export PJRT_DEVICE=TPU
# prefer the native agent when baked into the image; fall back to the
# python agent shipped with the framework wheel
if command -v tpu-shim >/dev/null 2>&1; then
  nohup tpu-shim --port {SHIM_PORT} --base-dir /root/.dtpu/shim > /var/log/tpu-shim.log 2>&1 &
else
  python3 -m pip install -q dstack-tpu=={__version__} || true
  nohup python3 -m dstack_tpu.agent.python.shim_main --port {SHIM_PORT} \\
    --base-dir /root/.dtpu/shim > /var/log/tpu-shim.log 2>&1 &
fi
"""


GATEWAY_PORT = 8002


GATEWAY_VENVS_DIR = "/root/.dtpu/gateway-venvs"


def _gateway_venv_install(version: str) -> str:
    """Shell fragment: install ``dstack-tpu==version`` into a fresh
    versioned venv and atomically flip the ``current`` symlink to it —
    the blue/green step (reference base/compute.py:684-692 installs
    `/home/ubuntu/dstack/{{version}}` venvs the same way). The previous
    venv stays on disk for rollback; the symlink flip is `ln -sfn` via a
    temp name + rename so a crash mid-upgrade never leaves `current`
    dangling."""
    vdir = f"{GATEWAY_VENVS_DIR}/{version}"
    return f"""mkdir -p {GATEWAY_VENVS_DIR}
if [ ! -x {vdir}/bin/python ] || ! {vdir}/bin/python -c 'import dstack_tpu' 2>/dev/null; then
  python3 -m venv {vdir}
  {vdir}/bin/pip install -q dstack-tpu=={version} || {{ rm -rf {vdir}; exit 1; }}
fi
ln -s {vdir} {GATEWAY_VENVS_DIR}/.next.$$ && \\
  mv -T {GATEWAY_VENVS_DIR}/.next.$$ {GATEWAY_VENVS_DIR}/current"""


def get_gateway_startup_script(token: str, server_url: str = "") -> str:
    """Startup script for a gateway VM: nginx + certbot + the gateway
    agent in a versioned venv behind a ``current`` symlink, run as a
    systemd unit (reference base/compute.py:684-692 blue/green venv
    install + proxy/gateway/systemd/). The unit survives VM reboots
    (enabled) and agent crashes (Restart=always); upgrades install a
    NEW venv and flip the symlink (see get_gateway_upgrade_script) so
    a failed install never takes down the running version."""
    server_flag = (
        f" \\\n  --server-url {shlex.quote(server_url)}" if server_url else ""
    )
    return f"""#!/bin/bash
set -e
apt-get update -q && apt-get install -yq nginx certbot python3-certbot-nginx python3-pip python3-venv
mkdir -p /root/.dtpu
{_gateway_venv_install(__version__)}
cat > /etc/systemd/system/tpu-gateway.service <<'EOF'
[Unit]
Description=dstack-tpu gateway agent
After=network.target nginx.service
[Service]
ExecStart={GATEWAY_VENVS_DIR}/current/bin/python -m dstack_tpu.gateway.app --port {GATEWAY_PORT} \\
  --state-file /root/.dtpu/gateway-state.json --token {shlex.quote(token)} \\
  --nginx-conf-dir /etc/nginx/sites-enabled --access-log /var/log/nginx/access.log{server_flag}
Restart=always
RestartSec=2
[Install]
WantedBy=multi-user.target
EOF
systemctl daemon-reload
systemctl enable --now tpu-gateway
"""


def get_gateway_upgrade_script(version: str = __version__) -> str:
    """Blue/green gateway upgrade: install ``version`` into its own
    venv, flip the ``current`` symlink, restart the unit. State (and
    the served traffic's nginx configs) live outside the venv
    (`/root/.dtpu/gateway-state.json`, `/etc/nginx/sites-enabled`), so
    the new agent restores every service/replica on boot; a failed
    install leaves the symlink — and the running agent — untouched."""
    return f"""#!/bin/bash
set -e
{_gateway_venv_install(version)}
systemctl restart tpu-gateway
"""


class GCPTPUCompute(
    Compute,
    ComputeWithCreateInstanceSupport,
    ComputeWithMultinodeSupport,
    ComputeWithReservationSupport,
    ComputeWithVolumeSupport,
    ComputeWithGatewaySupport,
):
    """config: {"project_id": ..., "regions": [...], "network": ...}"""

    def __init__(self, config: dict, transport: Optional[Transport] = None):
        self.config = config
        self.project_id = config.get("project_id", "")
        self.regions = config.get("regions")
        self.api = TPUNodesAPI(self.project_id, transport=transport)
        self.gce = GCEInstancesAPI(self.project_id, transport=transport)

    async def get_offers(
        self, requirements: Requirements
    ) -> list[InstanceOfferWithAvailability]:
        items = query_slices(
            requirements.resources,
            regions=self.regions,
            spot=requirements.spot,
            max_price=requirements.max_price,
        )
        offers = []
        for item in items:
            if item.region not in TPU_ZONES:
                continue
            offers.append(
                InstanceOfferWithAvailability(
                    backend=BackendType.GCP,
                    instance=InstanceType(
                        name=item.instance_name, resources=item.resources
                    ),
                    region=item.region,
                    price=item.price,
                    availability=InstanceAvailability.UNKNOWN,
                    availability_zones=[TPU_ZONES[item.region]],
                )
            )
        return offers

    async def create_instance(
        self,
        instance_offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
    ) -> JobProvisioningData:
        tpu = instance_offer.instance.resources.tpu
        if tpu is None:
            raise ComputeError("GCP backend only provisions TPU slices")
        zone = (
            instance_config.availability_zone
            or (instance_offer.availability_zones or [None])[0]
            or TPU_ZONES[instance_offer.region]
        )
        node_id = f"dtpu-{instance_config.instance_name}"[:60].rstrip("-")
        script = get_shim_startup_script(
            instance_config.ssh_public_keys, tpu.version
        )
        spot = instance_offer.instance.resources.spot
        # volumes attach as TPU data disks at node creation (the
        # UpdateNode path in attach_volume covers reused instances)
        data_disks = [
            {
                "sourceDisk": f"projects/{self.project_id}/zones/{zone}/disks/{vid}",
                "mode": "READ_WRITE",
            }
            for vid in instance_config.volume_ids
        ]
        used_qr = False
        try:
            if tpu.hosts > 4 or instance_config.reservation:
                used_qr = True
                # big slices go through the queued-resources path
                # (atomic all-workers admission)
                await self.api.create_queued_resource(
                    zone=zone,
                    resource_id=f"{node_id}-qr",
                    node_id=node_id,
                    accelerator_type=tpu.accelerator_type,
                    runtime_version=runtime_version_for(tpu.version),
                    startup_script=script,
                    spot=spot,
                    network=self.config.get("network", "default"),
                    labels={"dtpu-project": instance_config.project_name},
                    reservation=instance_config.reservation,
                    data_disks=data_disks,
                )
            else:
                await self.api.create_node(
                    zone=zone,
                    node_id=node_id,
                    accelerator_type=tpu.accelerator_type,
                    runtime_version=runtime_version_for(tpu.version),
                    startup_script=script,
                    spot=spot,
                    network=self.config.get("network", "default"),
                    labels={"dtpu-project": instance_config.project_name},
                    reservation=instance_config.reservation,
                    data_disks=data_disks,
                )
        except BackendError as e:
            raise ComputeError(str(e)) from e
        return JobProvisioningData(
            backend=BackendType.GCP,
            instance_type=instance_offer.instance,
            instance_id=node_id,
            hostname=None,  # filled by update_provisioning_data polling
            region=instance_offer.region,
            availability_zone=zone,
            price=instance_offer.price,
            username="root",
            ssh_port=22,
            dockerized=True,
            hosts=[],
            backend_data=json.dumps(
                {"zone": zone, "node_id": node_id, "queued_resource": used_qr}
            ),
        )

    async def update_provisioning_data(
        self, provisioning_data: JobProvisioningData
    ) -> JobProvisioningData:
        bd = json.loads(provisioning_data.backend_data or "{}")
        zone, node_id = bd.get("zone"), bd.get("node_id")
        if not zone or not node_id:
            return provisioning_data
        node = await self.api.get_node(zone, node_id)
        state = node.get("state")
        if state in ("CREATING", "STARTING", "PENDING", None):
            return provisioning_data
        if state in ("PREEMPTED", "TERMINATED", "FAILED"):
            raise ComputeError(f"TPU node {node_id} entered state {state}")
        endpoints = node.get("networkEndpoints") or []
        tpu = provisioning_data.instance_type.resources.tpu
        expected = tpu.hosts if tpu else 1
        if len(endpoints) < expected:
            return provisioning_data  # not all workers up yet
        hosts = []
        for wid, ep in enumerate(endpoints):
            external = (ep.get("accessConfig") or {}).get("externalIp")
            hosts.append(
                HostMetadata(
                    worker_id=wid,
                    internal_ip=ep.get("ipAddress", ""),
                    external_ip=external,
                    shim_port=SHIM_PORT,
                )
            )
        provisioning_data.hosts = hosts
        provisioning_data.hostname = hosts[0].external_ip or hosts[0].internal_ip
        provisioning_data.internal_ip = hosts[0].internal_ip
        return provisioning_data

    async def terminate_instance(
        self, instance_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        bd = json.loads(backend_data or "{}")
        zone = bd.get("zone") or TPU_ZONES.get(region)
        node_id = bd.get("node_id") or instance_id
        if zone is None:
            return
        try:
            await self.api.delete_node(zone, node_id)
        except BackendError as e:
            if "404" not in str(e):
                raise
        if bd.get("queued_resource"):
            # a WAITING queued resource would otherwise admit a slice
            # nobody tracks (and block name reuse) — force-delete it
            try:
                await self.api.delete_queued_resource(zone, f"{node_id}-qr")
            except BackendError as e:
                if "404" not in str(e):
                    logger.warning("queued resource cleanup failed: %s", e)

    # ---- gateways: plain GCE VMs running the gateway agent ----

    async def create_gateway(self, name: str, region: str) -> dict:
        import secrets as _secrets

        zone = TPU_ZONES.get(region)
        if zone is None:
            raise ComputeError(f"no known zone for region {region}")
        token = _secrets.token_hex(16)
        vm_name = f"dtpu-gateway-{name}"
        # default VPC rules cover only 80/443; the agent port needs its own
        await self.gce.ensure_firewall_rule(
            "dtpu-gateway-allow-agent", "tpu-gateway", ["80", "443", str(GATEWAY_PORT)]
        )
        from dstack_tpu.server import settings

        await self.gce.create_instance(
            zone,
            vm_name,
            startup_script=get_gateway_startup_script(token, settings.SERVER_URL),
            tags=["tpu-gateway", "http-server", "https-server"],
        )
        # the insert is async; the VM may not be queryable yet — the
        # reconciler polls update_gateway_provisioning_data for the IP
        ip = None
        try:
            inst = await self.gce.get_instance(zone, vm_name)
        except BackendError:
            inst = {}
        for ni in inst.get("networkInterfaces", []):
            for ac in ni.get("accessConfigs", []):
                if ac.get("natIP"):
                    ip = ac["natIP"]
        return {
            "instance_id": vm_name,
            "ip_address": ip,
            "region": region,
            "availability_zone": zone,
            "agent_port": GATEWAY_PORT,
            "agent_token": token,
        }

    async def terminate_gateway(self, instance_id: str, region: str) -> None:
        zone = TPU_ZONES.get(region)
        if zone is None:
            return
        try:
            await self.gce.delete_instance(zone, instance_id)
        except BackendError as e:
            if "404" not in str(e):
                raise

    async def update_gateway_provisioning_data(self, pd: dict) -> dict:
        if pd.get("ip_address"):
            return pd
        zone = pd.get("availability_zone") or TPU_ZONES.get(pd.get("region", ""))
        if zone is None:
            return pd
        inst = await self.gce.get_instance(zone, pd["instance_id"])
        for ni in inst.get("networkInterfaces", []):
            for ac in ni.get("accessConfigs", []):
                if ac.get("natIP"):
                    pd["ip_address"] = ac["natIP"]
        return pd

    # ---- volumes: persistent disks attached to TPU nodes ----

    async def create_volume(self, volume: Volume) -> VolumeProvisioningData:
        """Create a persistent disk (reference gcp/compute.py:561-676
        creates disks via the google-cloud SDK; here the REST API) and
        poll it to READY. TPU nodes attach it as a dataDisk — at node
        creation for fresh slices, via UpdateNode for reused ones."""
        conf = volume.configuration
        zone = conf.availability_zone or TPU_ZONES.get(conf.region or "", "")
        if not zone:
            raise ComputeError(
                "volume needs availability_zone or a known region"
            )
        size_gb = int(conf.size or 100)
        # project-scoped name: same-named volumes in different dstack
        # projects must not collide inside one GCP project
        disk_name = f"dtpu-{volume.project_name}-{volume.name}"[:60].rstrip("-")
        await self.gce.create_disk(zone, disk_name, size_gb)
        from dstack_tpu.utils.retry import (
            Deadline,
            DeadlineExceeded,
            wait_for_async,
        )

        async def _ready():
            disk = await self.gce.get_disk(zone, disk_name)
            status = disk.get("status", "")
            if status == "FAILED":
                raise ComputeError(f"disk {disk_name} entered FAILED state")
            return status if status == "READY" else None

        try:
            await wait_for_async(
                _ready,
                site="gcp.disk_ready",
                interval=2.0,
                deadline=Deadline(60.0),
            )
        except DeadlineExceeded:
            raise ComputeError(
                f"disk {disk_name} not READY after 60s"
            ) from None
        return VolumeProvisioningData(
            backend=BackendType.GCP,
            volume_id=disk_name,
            size_gb=size_gb,
            availability_zone=zone,
            backend_data=json.dumps({"created": True}),
        )

    async def register_volume(self, volume: Volume) -> VolumeProvisioningData:
        return VolumeProvisioningData(
            backend=BackendType.GCP,
            volume_id=volume.configuration.volume_id or volume.name,
            size_gb=volume.configuration.size or 0,
            availability_zone=volume.configuration.availability_zone,
        )

    async def delete_volume(self, volume: Volume) -> None:
        """Delete disks the framework created; registered external disks
        are left alone."""
        pd = volume.provisioning_data
        if pd is None or volume.external:
            return
        created = bool(json.loads(pd.backend_data or "{}").get("created"))
        if not created or not pd.availability_zone:
            return
        try:
            await self.gce.delete_disk(pd.availability_zone, pd.volume_id)
        except Exception as e:
            if "404" not in str(e):
                raise

    async def attach_volume(self, volume: Volume, instance_id: str) -> VolumeAttachmentData:
        pd = volume.provisioning_data
        if pd is None:
            raise ComputeError("volume has no provisioning data")
        zone = pd.availability_zone or ""
        disk = (
            f"projects/{self.project_id}/zones/{zone}/disks/{pd.volume_id}"
        )
        node = await self.api.get_node(zone, instance_id)
        disks = node.get("dataDisks") or []
        disks.append({"sourceDisk": disk, "mode": "READ_WRITE"})
        await self.api.update_node_disks(zone, instance_id, disks)
        return VolumeAttachmentData(device_name=f"persistent-disk-{len(disks)}")

    async def detach_volume(self, volume: Volume, instance_id: str) -> None:
        pd = volume.provisioning_data
        if pd is None:
            return
        zone = pd.availability_zone or ""
        node = await self.api.get_node(zone, instance_id)
        disks = [
            d
            for d in (node.get("dataDisks") or [])
            if not d.get("sourceDisk", "").endswith("/" + pd.volume_id)
        ]
        await self.api.update_node_disks(zone, instance_id, disks)
