"""Kubernetes (GKE TPU) backend.

Parity: reference core/backends/kubernetes (616 LoC: jobs as pods +
jump-pod NodePort for reachability). TPU-first redesign: pods request
``google.com/tpu`` resources and are pinned to GKE TPU node pools via
the standard selectors (``cloud.google.com/gke-tpu-accelerator``,
``cloud.google.com/gke-tpu-topology``); the pod runs the dtpu agent
(shim in process mode) so the normal shim→runner flow applies, reached
through a NodePort service instead of SSH.

**Multi-host slices (beyond the reference's single-host TPU support)**:
nodes whose ``gke-tpu-topology`` spans more chips than one node holds
are one host of a multi-host slice pool. When the pool has enough
nodes, the whole slice is offered as ONE instance (the same
slice-as-instance shape the GCP backend uses) and provisioned as a
gang: one agent pod per worker, each pinned by ``nodeName`` to a
distinct pool node (JobSet-style placement without the JobSet CRD),
each with its own NodePort service. The server's normal slice
rendezvous (TPU_WORKER_ID/HOSTNAMES via cluster_info) then applies
unchanged. DCN multislice (``slices > 1``) stays refused on this
backend.

Offers are derived from the cluster's live nodes (the reference does the
same: capacity is whatever the cluster has).
"""

from typing import Optional

from dstack_tpu.backends.base.compute import (
    Compute,
    ComputeWithCreateInstanceSupport,
    ComputeWithMultinodeSupport,
)
from dstack_tpu.backends.kubernetes.api import KubernetesAPI
from dstack_tpu.core.errors import ComputeError
from dstack_tpu.core.models.backends import BackendType
from dstack_tpu.core.models.instances import (
    HostMetadata,
    InstanceAvailability,
    InstanceConfiguration,
    InstanceOfferWithAvailability,
    InstanceType,
    Resources,
    TPUInfo,
)
from dstack_tpu.core.models.resources import topology_chips
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.utils.common import run_async
from dstack_tpu.utils.logging import get_logger

logger = get_logger("backends.kubernetes")

SHIM_PORT = 10998
# process-mode runners allocate ports monotonically from 11000 and never
# reuse them — expose enough for job retries on the same pod
RUNNER_PORT_RANGE = (11000, 11010)
SSH_PORT = 10022

# GKE TPU accelerator label → (generation, chips per host)
GKE_TPU_TYPES = {
    "tpu-v4-podslice": ("v4", 4),
    "tpu-v5-lite-podslice": ("v5e", 8),
    "tpu-v5-lite-device": ("v5e", 8),
    "tpu-v5p-slice": ("v5p", 4),
    "tpu-v6e-slice": ("v6e", 8),
}


def _parse_quantity(q) -> int:
    """K8s resource quantity → integer units (handles m/Ki/Mi/Gi)."""
    if q is None:
        return 0
    s = str(q)
    mult = 1
    for suffix, m in (
        ("Ki", 1024), ("Mi", 1024**2), ("Gi", 1024**3), ("Ti", 1024**4)
    ):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)])) * m
    if s.endswith("m"):
        return max(1, int(s[:-1]) // 1000)
    return int(float(s) * mult)


class _SlicePool:
    """Nodes forming one multi-host GKE TPU slice."""

    def __init__(self, pool_id, accel, version, topology, region,
                 chips_per_node, hosts_needed, total_chips):
        self.pool_id = pool_id  # GKE node-pool name: one physical slice set
        self.accel = accel
        self.version = version
        self.topology = topology
        self.region = region
        self.chips_per_node = chips_per_node
        self.hosts_needed = hosts_needed
        self.total_chips = total_chips
        self.node_names: list[str] = []
        self.cpus = 0
        self.memory_mib = 0

    def add_node(self, name: str, cpus: int, memory_mib: int) -> None:
        self.node_names.append(name)
        # slice-as-instance offers report WHOLE-SLICE totals (the GCP
        # catalog multiplies host resources by hosts the same way)
        self.cpus += cpus
        self.memory_mib += memory_mib

    def offer(self, price: float):
        if len(self.node_names) < self.hosts_needed:
            return None  # incomplete pool: the slice cannot form
        return InstanceOfferWithAvailability(
            backend=BackendType.KUBERNETES,
            instance=InstanceType(
                name=f"slice-{self.pool_id}-{self.topology}",
                resources=Resources(
                    cpus=self.cpus,
                    memory_mib=self.memory_mib,
                    tpu=TPUInfo(
                        version=self.version,
                        chips=self.total_chips,
                        topology=self.topology,
                        hosts=self.hosts_needed,
                        chips_per_host=self.chips_per_node,
                    ),
                ),
            ),
            region=self.region,
            price=price * self.hosts_needed,
            availability=InstanceAvailability.AVAILABLE,
        )


class KubernetesCompute(
    Compute, ComputeWithCreateInstanceSupport, ComputeWithMultinodeSupport
):
    """``config``: {api_server, token, namespace?, verify_ssl?,
    ca_cert_path?, agent_image?, node_price_per_hour?}."""

    def __init__(self, config: dict, api: Optional[KubernetesAPI] = None):
        self.config = config
        if api is None:
            if not config.get("api_server") or not config.get("token"):
                raise ComputeError(
                    "kubernetes backend requires api_server and token"
                )
            api = KubernetesAPI(
                api_server=config["api_server"],
                token=config["token"],
                namespace=config.get("namespace", "default"),
                verify_ssl=config.get("verify_ssl", False),
                ca_cert_path=config.get("ca_cert_path"),
            )
        self.api = api
        self.agent_image = config.get("agent_image", "python:3.12-slim")
        self.price = float(config.get("node_price_per_hour", 0.0))

    # -- offers --

    @staticmethod
    def _node_facts(node: dict) -> Optional[dict]:
        """One parse of a node's labels/allocatable, shared by the
        single-host offer path and the slice-pool grouping."""
        labels = node["metadata"].get("labels", {})
        alloc = node.get("status", {}).get("allocatable", {})
        cpus = _parse_quantity(alloc.get("cpu"))
        if cpus <= 0:
            return None
        facts = {
            "name": node["metadata"]["name"],
            "cpus": cpus,
            "memory_mib": _parse_quantity(alloc.get("memory")) // (1024 * 1024),
            "region": labels.get("topology.kubernetes.io/region", "cluster"),
            "nodepool": labels.get("cloud.google.com/gke-nodepool", ""),
            "tpu_count": 0,
        }
        accel = labels.get("cloud.google.com/gke-tpu-accelerator")
        tpu_count = _parse_quantity(alloc.get("google.com/tpu"))
        if accel and accel in GKE_TPU_TYPES and tpu_count > 0:
            topology = labels.get(
                "cloud.google.com/gke-tpu-topology", f"1x{tpu_count}"
            )
            try:
                topo_chips = topology_chips(topology)
            except ValueError:
                logger.warning(
                    "kubernetes node %s: malformed gke-tpu-topology label "
                    "%r; skipping node", node["metadata"]["name"], topology,
                )
                return None
            version, chips_per_host = GKE_TPU_TYPES[accel]
            facts.update(
                accel=accel, version=version, chips_per_host=chips_per_host,
                tpu_count=tpu_count, topology=topology, topo_chips=topo_chips,
            )
        return facts

    def _node_offer(self, node: dict) -> Optional[InstanceOfferWithAvailability]:
        facts = self._node_facts(node)
        if facts is None:
            return None
        tpu = None
        if facts["tpu_count"] > 0:
            if facts["topo_chips"] > facts["tpu_count"]:
                # one HOST of a multi-host slice pool: never offered
                # alone (a lone pod pinned here hangs in TPU runtime
                # init); get_offers aggregates the pool into one
                # gang-scheduled slice offer instead
                return None
            tpu = TPUInfo(
                version=facts["version"],
                chips=facts["tpu_count"],
                topology=facts["topology"],
                hosts=1,
                chips_per_host=facts["chips_per_host"],
            )
        return InstanceOfferWithAvailability(
            backend=BackendType.KUBERNETES,
            instance=InstanceType(
                name=facts["name"],
                resources=Resources(
                    cpus=facts["cpus"], memory_mib=facts["memory_mib"], tpu=tpu
                ),
            ),
            region=facts["region"],
            price=self.price,
            availability=InstanceAvailability.AVAILABLE,
        )

    async def get_offers(
        self, requirements: Requirements
    ) -> list[InstanceOfferWithAvailability]:
        res = requirements.resources
        if res.tpu is not None and (res.tpu.slices or 1) > 1:
            # multislice needs gang scheduling (JobSet); refuse loudly
            # here so get_plan can tell the user at apply time instead
            # of a late scheduler no-capacity failure
            logger.warning(
                "kubernetes backend: multislice TPU request refused "
                "(no gang scheduling; use the gcp backend)"
            )
            return []
        nodes = await run_async(self.api.list_nodes)
        offers = []
        for node in nodes:
            offer = self._node_offer(node)
            if offer is None:
                continue
            tpu = offer.instance.resources.tpu
            if res.tpu is not None:
                if tpu is None:
                    continue
                if res.tpu.version is not None and tpu.version not in res.tpu.version:
                    continue
                if not res.tpu.chips.contains(tpu.chips):
                    continue
            offers.append(offer)
        for pool in self._slice_pools(nodes).values():
            offer = pool.offer(self.price)
            if offer is None:
                continue
            tpu = offer.instance.resources.tpu
            if res.tpu is not None:
                if res.tpu.version is not None and tpu.version not in res.tpu.version:
                    continue
                if not res.tpu.chips.contains(tpu.chips):
                    continue
                if res.tpu.topology is not None and tpu.topology != res.tpu.topology:
                    continue
            elif tpu is not None:
                continue  # don't waste a whole slice on a CPU job
            offers.append(offer)
        return offers

    def _slice_pools(self, nodes: list) -> dict:
        """Group multi-host slice-pool nodes by GKE NODE POOL — one
        physical slice's ICI-connected hosts. Grouping any looser (e.g.
        by accelerator+topology alone) could gang pods across two
        unconnected slices, whose TPU rendezvous would hang."""
        pools: dict = {}
        for node in nodes:
            facts = self._node_facts(node)
            if facts is None or facts["tpu_count"] <= 0:
                continue
            if facts["topo_chips"] <= facts["tpu_count"]:
                continue  # single-host node, offered individually
            # GKE stamps every node with its node pool; clusters without
            # the label fall back to grouping by shape alone, which
            # cannot distinguish two identical slices — acceptable only
            # because GKE (the TPU case) always labels
            pool_id = facts["nodepool"] or f"{facts['accel']}-pool"
            key = (pool_id, facts["accel"], facts["topology"], facts["region"])
            pool = pools.get(key)
            if pool is None:
                pool = pools[key] = _SlicePool(
                    pool_id=pool_id,
                    accel=facts["accel"],
                    version=facts["version"],
                    topology=facts["topology"],
                    region=facts["region"],
                    chips_per_node=facts["tpu_count"],
                    hosts_needed=-(-facts["topo_chips"] // facts["tpu_count"]),
                    total_chips=facts["topo_chips"],
                )
            pool.add_node(facts["name"], facts["cpus"], facts["memory_mib"])
        return pools

    # -- provisioning --

    def _pod_name(self, instance_name: str) -> str:
        return f"dtpu-{instance_name}"[:60].rstrip("-").lower()

    def _manifests(
        self,
        pod_name: str,
        offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
        node_name: Optional[str] = None,
    ) -> tuple[dict, dict]:
        tpu = offer.instance.resources.tpu
        resources: dict = {}
        node_selector: dict = {}
        if tpu is not None:
            # a multi-host slice worker pod asks for ITS node's chips,
            # not the whole slice's
            pod_chips = (
                tpu.chips_per_host if tpu.hosts > 1 else tpu.chips
            )
            resources = {
                "requests": {"google.com/tpu": str(pod_chips)},
                "limits": {"google.com/tpu": str(pod_chips)},
            }
            accel = next(
                (
                    k
                    for k, (v, _) in GKE_TPU_TYPES.items()
                    if v == tpu.version and "device" not in k
                ),
                None,
            )
            if accel:
                node_selector = {
                    "cloud.google.com/gke-tpu-accelerator": accel,
                    "cloud.google.com/gke-tpu-topology": tpu.topology,
                }
        ports = [SHIM_PORT, *range(RUNNER_PORT_RANGE[0], RUNNER_PORT_RANGE[1]), SSH_PORT]
        authorized = "\n".join(instance_config.ssh_public_keys)
        bootstrap = (
            "pip install --quiet aiohttp psutil pyyaml pydantic requests cryptography && "
            "mkdir -p /root/.ssh && chmod 700 /root/.ssh && "
            f"printf '%s\\n' \"$DTPU_AUTHORIZED_KEYS\" >> /root/.ssh/authorized_keys && "
            "chmod 600 /root/.ssh/authorized_keys && "
            # best-effort sshd so `dtpu attach`'s tunnel has a target;
            # the job itself does not depend on it
            "if ! command -v sshd >/dev/null 2>&1; then "
            "apt-get update -qq && apt-get install -y -qq openssh-server "
            ">/dev/null 2>&1 || true; fi; "
            "if command -v sshd >/dev/null 2>&1; then "
            "mkdir -p /run/sshd; ssh-keygen -A >/dev/null 2>&1; "
            f'"$(command -v sshd)" -p {SSH_PORT} -o PermitRootLogin=yes '
            "-o PasswordAuthentication=no || true; fi; "
            "python -m dstack_tpu.agent.python.shim_main "
            f"--port {SHIM_PORT} --base-dir /root/.dtpu --runtime process"
        )
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "labels": {"app": "dtpu", "dtpu-instance": pod_name},
            },
            "spec": {
                "restartPolicy": "Never",
                **({"nodeName": node_name} if node_name else {}),
                "nodeSelector": node_selector,
                "containers": [
                    {
                        "name": "agent",
                        "image": self.agent_image,
                        "command": ["/bin/sh", "-c", bootstrap],
                        "env": [
                            {"name": "PJRT_DEVICE", "value": "TPU"},
                            {
                                "name": "DTPU_AUTHORIZED_KEYS",
                                "value": authorized,
                            },
                        ],
                        "ports": [{"containerPort": p} for p in ports],
                        "resources": resources,
                        "securityContext": {"privileged": tpu is not None},
                    }
                ],
            },
        }
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": pod_name},
            "spec": {
                "type": "NodePort",
                "selector": {"dtpu-instance": pod_name},
                "ports": [
                    {"name": f"p{p}", "port": p, "targetPort": p} for p in ports
                ],
            },
        }
        return pod, service

    async def create_instance(
        self,
        instance_offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
    ) -> JobProvisioningData:
        import json

        base = self._pod_name(instance_config.instance_name)
        tpu = instance_offer.instance.resources.tpu
        if tpu is not None and tpu.hosts > 1:
            # gang scheduling: one worker pod per pool node, pinned by
            # nodeName so the set lands on exactly the slice's hosts
            nodes = await run_async(self.api.list_nodes)
            pool = next(
                (
                    p for p in self._slice_pools(nodes).values()
                    if f"slice-{p.pool_id}-{p.topology}"
                    == instance_offer.instance.name
                    and len(p.node_names) >= tpu.hosts
                ),
                None,
            )
            if pool is None:
                raise ComputeError(
                    f"no complete {tpu.version} {tpu.topology} slice pool "
                    "in the cluster anymore"
                )
            pod_names = [f"{base[:55]}-w{i}" for i in range(tpu.hosts)]
            created: list[str] = []
            try:
                for name, node_name in zip(pod_names, pool.node_names):
                    pod, service = self._manifests(
                        name, instance_offer, instance_config,
                        node_name=node_name,
                    )
                    await run_async(self.api.create_pod, pod)
                    created.append(name)
                    await run_async(self.api.create_service, service)
            except Exception:
                # all-or-nothing: a partial gang is torn down
                for name in created:
                    await run_async(self.api.delete_service, name)
                    await run_async(self.api.delete_pod, name)
                raise
            backend_data = json.dumps({"pods": pod_names})
            instance_id = pod_names[0]
        else:
            pod, service = self._manifests(base, instance_offer, instance_config)
            await run_async(self.api.create_pod, pod)
            try:
                await run_async(self.api.create_service, service)
            except Exception:
                await run_async(self.api.delete_pod, base)
                raise
            backend_data = None
            instance_id = base
        return JobProvisioningData(
            backend=BackendType.KUBERNETES,
            instance_type=instance_offer.instance,
            instance_id=instance_id,
            hostname=None,  # filled by update_provisioning_data
            region=instance_offer.region,
            price=instance_offer.price,
            username="root",
            ssh_port=SSH_PORT,
            dockerized=True,  # pod runs the shim; normal shim→runner flow
            backend_data=backend_data,
        )

    async def _pod_host(self, pod_name: str, worker_id: int):
        """One worker's HostMetadata, or None while it is not Running."""
        pod = await run_async(self.api.get_pod, pod_name)
        if pod is None:
            return None
        status = pod.get("status", {})
        host_ip = status.get("hostIP")
        pod_ip = status.get("podIP")
        if status.get("phase") != "Running" or not host_ip:
            return None
        svc = await run_async(self.api.get_service, pod_name)
        port_map: dict[str, int] = {}
        if svc is not None:
            for p in svc.get("spec", {}).get("ports", []):
                if p.get("nodePort"):
                    port_map[str(p["port"])] = int(p["nodePort"])
        return HostMetadata(
            worker_id=worker_id,
            internal_ip=pod_ip or host_ip,
            external_ip=host_ip,
            shim_port=int(port_map.get(str(SHIM_PORT), SHIM_PORT)),
            port_map=port_map,
        )

    async def update_provisioning_data(
        self, provisioning_data: JobProvisioningData
    ) -> JobProvisioningData:
        import json

        pods = json.loads(provisioning_data.backend_data or "{}").get(
            "pods"
        ) or [provisioning_data.instance_id]
        hosts = []
        for wid, name in enumerate(pods):
            host = await self._pod_host(name, wid)
            if host is None:
                return provisioning_data  # gang not fully Running yet
            hosts.append(host)
        provisioning_data.hosts = hosts
        provisioning_data.hostname = hosts[0].external_ip
        provisioning_data.internal_ip = hosts[0].internal_ip
        provisioning_data.ssh_port = int(
            (hosts[0].port_map or {}).get(str(SSH_PORT), SSH_PORT)
        )
        return provisioning_data

    async def terminate_instance(
        self, instance_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        import json

        pods = json.loads(backend_data or "{}").get("pods") or [instance_id]
        for name in pods:
            await run_async(self.api.delete_service, name)
            await run_async(self.api.delete_pod, name)
