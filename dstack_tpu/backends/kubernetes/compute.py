"""Kubernetes (GKE TPU) backend.

Parity: reference core/backends/kubernetes (616 LoC: jobs as pods +
jump-pod NodePort for reachability). TPU-first redesign: pods request
``google.com/tpu`` resources and are pinned to GKE TPU node pools via
the standard selectors (``cloud.google.com/gke-tpu-accelerator``,
``cloud.google.com/gke-tpu-topology``); the pod runs the dtpu agent
(shim in process mode) so the normal shim→runner flow applies, reached
through a NodePort service instead of SSH.

Single-host TPU slices per pod (like the reference's TPU support);
multi-host GKE slices need JobSet-style gang scheduling — the GCP
``tpu_v2`` backend is the multi-host path in this framework.

Offers are derived from the cluster's live nodes (the reference does the
same: capacity is whatever the cluster has).
"""

from typing import Optional

from dstack_tpu.backends.base.compute import (
    Compute,
    ComputeWithCreateInstanceSupport,
)
from dstack_tpu.backends.kubernetes.api import KubernetesAPI
from dstack_tpu.core.errors import ComputeError
from dstack_tpu.core.models.backends import BackendType
from dstack_tpu.core.models.instances import (
    HostMetadata,
    InstanceAvailability,
    InstanceConfiguration,
    InstanceOfferWithAvailability,
    InstanceType,
    Resources,
    TPUInfo,
)
from dstack_tpu.core.models.runs import JobProvisioningData, Requirements
from dstack_tpu.utils.common import run_async
from dstack_tpu.utils.logging import get_logger

logger = get_logger("backends.kubernetes")

SHIM_PORT = 10998
# process-mode runners allocate ports monotonically from 11000 and never
# reuse them — expose enough for job retries on the same pod
RUNNER_PORT_RANGE = (11000, 11010)
SSH_PORT = 10022

# GKE TPU accelerator label → (generation, chips per host)
GKE_TPU_TYPES = {
    "tpu-v4-podslice": ("v4", 4),
    "tpu-v5-lite-podslice": ("v5e", 8),
    "tpu-v5-lite-device": ("v5e", 8),
    "tpu-v5p-slice": ("v5p", 4),
    "tpu-v6e-slice": ("v6e", 8),
}


def _parse_quantity(q) -> int:
    """K8s resource quantity → integer units (handles m/Ki/Mi/Gi)."""
    if q is None:
        return 0
    s = str(q)
    mult = 1
    for suffix, m in (
        ("Ki", 1024), ("Mi", 1024**2), ("Gi", 1024**3), ("Ti", 1024**4)
    ):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)])) * m
    if s.endswith("m"):
        return max(1, int(s[:-1]) // 1000)
    return int(float(s) * mult)


class KubernetesCompute(Compute, ComputeWithCreateInstanceSupport):
    """``config``: {api_server, token, namespace?, verify_ssl?,
    ca_cert_path?, agent_image?, node_price_per_hour?}."""

    def __init__(self, config: dict, api: Optional[KubernetesAPI] = None):
        self.config = config
        if api is None:
            if not config.get("api_server") or not config.get("token"):
                raise ComputeError(
                    "kubernetes backend requires api_server and token"
                )
            api = KubernetesAPI(
                api_server=config["api_server"],
                token=config["token"],
                namespace=config.get("namespace", "default"),
                verify_ssl=config.get("verify_ssl", False),
                ca_cert_path=config.get("ca_cert_path"),
            )
        self.api = api
        self.agent_image = config.get("agent_image", "python:3.12-slim")
        self.price = float(config.get("node_price_per_hour", 0.0))

    # -- offers --

    def _node_offer(self, node: dict) -> Optional[InstanceOfferWithAvailability]:
        labels = node["metadata"].get("labels", {})
        alloc = node.get("status", {}).get("allocatable", {})
        cpus = _parse_quantity(alloc.get("cpu"))
        memory_mib = _parse_quantity(alloc.get("memory")) // (1024 * 1024)
        if cpus <= 0:
            return None
        tpu = None
        accel = labels.get("cloud.google.com/gke-tpu-accelerator")
        tpu_count = _parse_quantity(alloc.get("google.com/tpu"))
        if accel and accel in GKE_TPU_TYPES and tpu_count > 0:
            version, chips_per_host = GKE_TPU_TYPES[accel]
            topology = labels.get(
                "cloud.google.com/gke-tpu-topology", f"1x{tpu_count}"
            )
            from dstack_tpu.core.models.resources import topology_chips

            try:
                topo_chips = topology_chips(topology)
            except ValueError:
                logger.warning(
                    "kubernetes node %s: malformed gke-tpu-topology label "
                    "%r; skipping node", node["metadata"]["name"], topology,
                )
                return None
            if topo_chips > tpu_count:
                # the node is ONE HOST of a multi-host slice pool
                # (topology spans more chips than this node holds): a
                # lone pod pinned here would hang in TPU runtime init —
                # gang scheduling is the GCP backend's job
                logger.warning(
                    "kubernetes node %s is part of a multi-host TPU "
                    "slice (%s topology, %d chips/node); skipping — "
                    "no gang scheduling on this backend",
                    node["metadata"]["name"], topology, tpu_count,
                )
                return None
            tpu = TPUInfo(
                version=version,
                chips=tpu_count,
                topology=topology,
                hosts=1,
                chips_per_host=chips_per_host,
            )
        region = labels.get("topology.kubernetes.io/region", "cluster")
        name = node["metadata"]["name"]
        return InstanceOfferWithAvailability(
            backend=BackendType.KUBERNETES,
            instance=InstanceType(
                name=name,
                resources=Resources(cpus=cpus, memory_mib=memory_mib, tpu=tpu),
            ),
            region=region,
            price=self.price,
            availability=InstanceAvailability.AVAILABLE,
        )

    async def get_offers(
        self, requirements: Requirements
    ) -> list[InstanceOfferWithAvailability]:
        res = requirements.resources
        if res.tpu is not None and (res.tpu.slices or 1) > 1:
            # multislice needs gang scheduling (JobSet); refuse loudly
            # here so get_plan can tell the user at apply time instead
            # of a late scheduler no-capacity failure
            logger.warning(
                "kubernetes backend: multislice TPU request refused "
                "(no gang scheduling; use the gcp backend)"
            )
            return []
        nodes = await run_async(self.api.list_nodes)
        offers = []
        for node in nodes:
            offer = self._node_offer(node)
            if offer is None:
                continue
            tpu = offer.instance.resources.tpu
            if res.tpu is not None:
                if tpu is None:
                    continue
                if res.tpu.version is not None and tpu.version not in res.tpu.version:
                    continue
                if not res.tpu.chips.contains(tpu.chips):
                    continue
            offers.append(offer)
        return offers

    # -- provisioning --

    def _pod_name(self, instance_name: str) -> str:
        return f"dtpu-{instance_name}"[:60].rstrip("-").lower()

    def _manifests(
        self,
        pod_name: str,
        offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
    ) -> tuple[dict, dict]:
        tpu = offer.instance.resources.tpu
        resources: dict = {}
        node_selector: dict = {}
        if tpu is not None:
            resources = {
                "requests": {"google.com/tpu": str(tpu.chips)},
                "limits": {"google.com/tpu": str(tpu.chips)},
            }
            accel = next(
                (
                    k
                    for k, (v, _) in GKE_TPU_TYPES.items()
                    if v == tpu.version and "device" not in k
                ),
                None,
            )
            if accel:
                node_selector = {
                    "cloud.google.com/gke-tpu-accelerator": accel,
                    "cloud.google.com/gke-tpu-topology": tpu.topology,
                }
        ports = [SHIM_PORT, *range(RUNNER_PORT_RANGE[0], RUNNER_PORT_RANGE[1]), SSH_PORT]
        authorized = "\n".join(instance_config.ssh_public_keys)
        bootstrap = (
            "pip install --quiet aiohttp psutil pyyaml pydantic requests cryptography && "
            "mkdir -p /root/.ssh && chmod 700 /root/.ssh && "
            f"printf '%s\\n' \"$DTPU_AUTHORIZED_KEYS\" >> /root/.ssh/authorized_keys && "
            "chmod 600 /root/.ssh/authorized_keys && "
            # best-effort sshd so `dtpu attach`'s tunnel has a target;
            # the job itself does not depend on it
            "if ! command -v sshd >/dev/null 2>&1; then "
            "apt-get update -qq && apt-get install -y -qq openssh-server "
            ">/dev/null 2>&1 || true; fi; "
            "if command -v sshd >/dev/null 2>&1; then "
            "mkdir -p /run/sshd; ssh-keygen -A >/dev/null 2>&1; "
            f'"$(command -v sshd)" -p {SSH_PORT} -o PermitRootLogin=yes '
            "-o PasswordAuthentication=no || true; fi; "
            "python -m dstack_tpu.agent.python.shim_main "
            f"--port {SHIM_PORT} --base-dir /root/.dtpu --runtime process"
        )
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "labels": {"app": "dtpu", "dtpu-instance": pod_name},
            },
            "spec": {
                "restartPolicy": "Never",
                "nodeSelector": node_selector,
                "containers": [
                    {
                        "name": "agent",
                        "image": self.agent_image,
                        "command": ["/bin/sh", "-c", bootstrap],
                        "env": [
                            {"name": "PJRT_DEVICE", "value": "TPU"},
                            {
                                "name": "DTPU_AUTHORIZED_KEYS",
                                "value": authorized,
                            },
                        ],
                        "ports": [{"containerPort": p} for p in ports],
                        "resources": resources,
                        "securityContext": {"privileged": tpu is not None},
                    }
                ],
            },
        }
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": pod_name},
            "spec": {
                "type": "NodePort",
                "selector": {"dtpu-instance": pod_name},
                "ports": [
                    {"name": f"p{p}", "port": p, "targetPort": p} for p in ports
                ],
            },
        }
        return pod, service

    async def create_instance(
        self,
        instance_offer: InstanceOfferWithAvailability,
        instance_config: InstanceConfiguration,
    ) -> JobProvisioningData:
        pod_name = self._pod_name(instance_config.instance_name)
        pod, service = self._manifests(pod_name, instance_offer, instance_config)
        await run_async(self.api.create_pod, pod)
        try:
            await run_async(self.api.create_service, service)
        except Exception:
            await run_async(self.api.delete_pod, pod_name)
            raise
        return JobProvisioningData(
            backend=BackendType.KUBERNETES,
            instance_type=instance_offer.instance,
            instance_id=pod_name,
            hostname=None,  # filled by update_provisioning_data
            region=instance_offer.region,
            price=instance_offer.price,
            username="root",
            ssh_port=SSH_PORT,
            dockerized=True,  # pod runs the shim; normal shim→runner flow
        )

    async def update_provisioning_data(
        self, provisioning_data: JobProvisioningData
    ) -> JobProvisioningData:
        pod_name = provisioning_data.instance_id
        pod = await run_async(self.api.get_pod, pod_name)
        if pod is None:
            return provisioning_data
        status = pod.get("status", {})
        host_ip = status.get("hostIP")
        pod_ip = status.get("podIP")
        if status.get("phase") != "Running" or not host_ip:
            return provisioning_data
        svc = await run_async(self.api.get_service, pod_name)
        port_map: dict[str, int] = {}
        if svc is not None:
            for p in svc.get("spec", {}).get("ports", []):
                if p.get("nodePort"):
                    port_map[str(p["port"])] = int(p["nodePort"])
        provisioning_data.hostname = host_ip
        provisioning_data.internal_ip = pod_ip or host_ip
        shim_nodeport = int(port_map.get(str(SHIM_PORT), SHIM_PORT))
        provisioning_data.ssh_port = int(port_map.get(str(SSH_PORT), SSH_PORT))
        provisioning_data.hosts = [
            HostMetadata(
                worker_id=0,
                internal_ip=pod_ip or host_ip,
                external_ip=host_ip,
                shim_port=shim_nodeport,
                port_map=port_map,
            )
        ]
        return provisioning_data

    async def terminate_instance(
        self, instance_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        await run_async(self.api.delete_service, instance_id)
        await run_async(self.api.delete_pod, instance_id)
