"""Minimal Kubernetes REST client.

The image bundles no kubernetes pip package, so this speaks the API
server's REST surface directly over ``requests`` with bearer-token auth
(the same calls kubectl makes). Only the endpoints the backend uses:
nodes, pods, services.

Parity: reference src/dstack/_internal/core/backends/kubernetes uses the
official client for the same operations (list nodes, create pod +
NodePort jump service).
"""

from typing import Any, Optional

import requests

from dstack_tpu.core.errors import BackendError


class KubernetesAPIError(BackendError):
    pass


class KubernetesAPI:
    def __init__(
        self,
        api_server: str,
        token: str,
        namespace: str = "default",
        verify_ssl: bool = False,
        ca_cert_path: Optional[str] = None,
    ):
        self.base = api_server.rstrip("/")
        self.namespace = namespace
        self._session = requests.Session()
        self._session.headers["Authorization"] = f"Bearer {token}"
        self._session.verify = ca_cert_path if ca_cert_path else verify_ssl

    def _request(
        self,
        method: str,
        path: str,
        json_body: Optional[dict] = None,
        ok_missing: bool = False,
    ) -> Any:
        resp = self._session.request(
            method, self.base + path, json=json_body, timeout=30
        )
        if resp.status_code == 404 and ok_missing:
            return None
        if resp.status_code >= 400:
            raise KubernetesAPIError(
                f"{method} {path}: {resp.status_code} {resp.text[:300]}"
            )
        return resp.json()

    # nodes

    def list_nodes(self) -> list[dict]:
        return self._request("GET", "/api/v1/nodes").get("items", [])

    # pods

    def create_pod(self, manifest: dict) -> dict:
        return self._request(
            "POST", f"/api/v1/namespaces/{self.namespace}/pods", manifest
        )

    def get_pod(self, name: str) -> Optional[dict]:
        return self._request(
            "GET",
            f"/api/v1/namespaces/{self.namespace}/pods/{name}",
            ok_missing=True,
        )

    def delete_pod(self, name: str) -> None:
        self._request(
            "DELETE",
            f"/api/v1/namespaces/{self.namespace}/pods/{name}",
            ok_missing=True,
        )

    # services

    def create_service(self, manifest: dict) -> dict:
        return self._request(
            "POST", f"/api/v1/namespaces/{self.namespace}/services", manifest
        )

    def get_service(self, name: str) -> Optional[dict]:
        return self._request(
            "GET",
            f"/api/v1/namespaces/{self.namespace}/services/{name}",
            ok_missing=True,
        )

    def delete_service(self, name: str) -> None:
        self._request(
            "DELETE",
            f"/api/v1/namespaces/{self.namespace}/services/{name}",
            ok_missing=True,
        )
