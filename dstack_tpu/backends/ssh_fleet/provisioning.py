"""SSH-fleet host adoption: install the shim on user-supplied TPU hosts.

Parity: reference remote/provisioning.py:99-204 (paramiko-based env
upload, shim installed as a systemd service, host-info JSON handshake,
consumed by process_instances._add_remote:214-385). No paramiko in this
image — the system ``ssh`` binary is used, and the command runner is
injectable so tests fake the wire.
"""

import asyncio
import json
import shlex
from typing import Awaitable, Callable, Optional

from dstack_tpu.agent import schemas as agent_schemas
from dstack_tpu.core.errors import ProvisioningError
from dstack_tpu.core.models.instances import RemoteConnectionInfo
from dstack_tpu.utils.logging import get_logger
from dstack_tpu.version import __version__

logger = get_logger("backends.ssh_fleet")

SHIM_PORT = 10998

SYSTEMD_UNIT = """\
[Unit]
Description=dstack-tpu shim
After=network.target

[Service]
Type=simple
ExecStart=/usr/bin/env python3 -m dstack_tpu.agent.python.shim_main \\
  --port {port} --base-dir /root/.dtpu/shim --service \\
  --host-info-path /root/.dtpu/host_info.json
Restart=always
RestartSec=2

[Install]
WantedBy=multi-user.target
"""

SSHRunner = Callable[[RemoteConnectionInfo, str], Awaitable[tuple[int, str]]]


async def default_ssh_run(rci: RemoteConnectionInfo, command: str) -> tuple[int, str]:
    """Run a command on the host via the system ssh binary."""
    cmd = [
        "ssh",
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", "ConnectTimeout=15",
        "-p", str(rci.port),
        f"{rci.ssh_user}@{rci.host}",
        command,
    ]
    proc = await asyncio.create_subprocess_exec(
        *cmd,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
    )
    out, _ = await proc.communicate()
    return proc.returncode or 0, out.decode(errors="replace")


async def adopt_host(
    rci: RemoteConnectionInfo,
    ssh_run: Optional[SSHRunner] = None,
) -> agent_schemas.HostInfo:
    """Install + start the shim service, return the host-info handshake."""
    run = ssh_run or default_ssh_run
    unit = SYSTEMD_UNIT.format(port=SHIM_PORT)
    install = (
        "set -e; "
        "python3 -c 'import dstack_tpu' 2>/dev/null || "
        f"python3 -m pip install -q dstack-tpu=={__version__}; "
        "mkdir -p /root/.dtpu; "
        f"printf %s {shlex.quote(unit)} > /etc/systemd/system/dtpu-shim.service; "
        "systemctl daemon-reload && systemctl enable --now dtpu-shim"
    )
    rc, out = await run(rci, install)
    if rc != 0:
        raise ProvisioningError(
            f"shim install failed on {rci.host}: {out[-400:]}"
        )
    # wait for the host-info handshake file written in --service mode
    from dstack_tpu.utils.retry import (
        Deadline,
        DeadlineExceeded,
        wait_for_async,
    )

    async def _handshake():
        rc, out = await run(rci, "cat /root/.dtpu/host_info.json 2>/dev/null")
        if rc == 0 and out.strip():
            try:
                return agent_schemas.HostInfo.model_validate(json.loads(out))
            except (json.JSONDecodeError, ValueError):
                pass
        return None

    try:
        return await wait_for_async(
            _handshake,
            site="ssh_fleet.host_info",
            interval=2.0,
            deadline=Deadline(60.0),
        )
    except DeadlineExceeded:
        raise ProvisioningError(
            f"no host-info handshake from {rci.host}"
        ) from None


async def remove_host(
    rci: RemoteConnectionInfo, ssh_run: Optional[SSHRunner] = None
) -> None:
    run = ssh_run or default_ssh_run
    await run(
        rci,
        "systemctl disable --now dtpu-shim 2>/dev/null; "
        "rm -f /etc/systemd/system/dtpu-shim.service",
    )
