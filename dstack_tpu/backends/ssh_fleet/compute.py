"""Remote (SSH fleet) backend.

Instances are user-supplied hosts; there is no offer market — fleet
apply creates PENDING instance rows with ``remote_connection_info`` and
``process_instances`` adopts them via :mod:`.provisioning`.
"""

from typing import Optional

from dstack_tpu.backends.base.compute import Compute, ComputeWithMultinodeSupport
from dstack_tpu.core.models.instances import InstanceOfferWithAvailability
from dstack_tpu.core.models.runs import Requirements


class SSHFleetCompute(Compute, ComputeWithMultinodeSupport):
    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}

    async def get_offers(
        self, requirements: Requirements
    ) -> list[InstanceOfferWithAvailability]:
        return []  # pool-only: jobs match adopted idle instances

    async def terminate_instance(
        self, instance_id: str, region: str, backend_data: Optional[str] = None
    ) -> None:
        # host remains the user's; the shim service is removed during
        # fleet deletion (process_instances → provisioning.remove_host)
        return None
