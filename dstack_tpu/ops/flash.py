"""Flash attention for TPU: pallas forward + backward kernels, custom VJP.

The hot op of the compute plane. Design (pallas_guide playbook):

- Grid ``(B, H, num_q_blocks, num_kv_blocks)`` with
  ``dimension_semantics = (parallel, parallel, parallel, arbitrary)`` —
  the KV dimension is innermost/sequential, so pallas streams KV blocks
  through VMEM with automatically double-buffered DMA while the online-
  softmax accumulators live in VMEM scratch across KV steps.
- HBM traffic is O(T·D) per query block (no [T, T] score matrix ever
  touches HBM); the MXU sees [BQ, D]×[D, BK] and [BQ, BK]×[BK, D]
  matmuls in f32 accumulation over bf16 inputs.
- GQA is native: the kernel's K/V index_map sends query head ``h`` to KV
  head ``h // group`` — no ``jnp.repeat`` materialization.
- Backward is two pallas kernels (dq; dk/dv) using the saved
  logsumexp — the standard FlashAttention-2 recomputation scheme.
- Causal blocks above the diagonal skip their compute via ``pl.when``.

The reference framework has no kernels to mirror (it is an orchestrator,
SURVEY.md §6); the bar is bench.py's 0.40-MFU target.

``q_offset``/``kv_offset`` place the local Q/KV blocks at global
positions for causal masking across sequence shards.
parallel/ring_attention.py drives the kernels directly per ring step
(`_flash_fwd`/`_flash_bwd`) and merges the per-step partials by the
returned logsumexp; ``flash_attention_with_lse`` exposes the same
(o, lse) pair publicly.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _pick_block(t: int, cap: int, unit: int = 128) -> int:
    """Largest multiple of ``unit`` that divides ``t`` and is ≤ cap."""
    if t % unit != 0:
        raise ValueError(f"sequence length {t} must be a multiple of {unit}")
    b = min(cap - cap % unit, t)
    while b > unit and t % b != 0:
        b -= unit
    return b


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, BK, D]
    v_ref,  # [1, 1, BK, D]
    o_ref,  # [1, 1, BQ, D]
    lse_ref,  # [1, 1, BQ, 1]
    acc_sc,  # VMEM [BQ, D] f32
    m_sc,  # VMEM [BQ, 128] f32
    l_sc,  # VMEM [BQ, 128] f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_k: int,
    q_offset: int,
    kv_offset: int,
    window: int,
    softcap: float,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    # global positions of this block's rows/cols
    q_lo = q_offset + qi * block_q
    k_lo = kv_offset + ki * block_k

    def compute():
        # inputs stay bf16 for the MXU; accumulation is f32
        q = q_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK] f32
        if softcap:
            s = softcap * jnp.tanh(s / softcap)  # cap raw scores, then mask
        if causal or window:
            rows = q_lo + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_lo + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            keep = rows >= cols if causal else rows == rows
            if window:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, NEG_INF)
        m_prev = m_sc[:, :1]  # [BQ, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # rows with no unmasked key yet keep exp(NEG_INF - NEG_INF) at 0
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(jnp.where(s <= NEG_INF / 2, NEG_INF, s) - m_safe)
        alpha = jnp.where(
            m_prev <= NEG_INF / 2, jnp.zeros_like(m_prev), jnp.exp(m_prev - m_safe)
        )
        l_sc[:, :1] = l_sc[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:, :1] = m_new

    live = None
    if causal:  # skip blocks strictly above the diagonal
        live = q_lo + block_q - 1 >= k_lo
    if window:  # skip blocks entirely below the sliding window
        below = k_lo + block_k - 1 >= q_lo - (window - 1)
        live = below if live is None else jnp.logical_and(live, below)
    if live is not None:
        pl.when(live)(compute)
    else:
        compute()

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        m = m_sc[:, :1]
        lse = jnp.where(
            l == 0.0, jnp.full_like(m, NEG_INF), m + jnp.log(l_safe)
        )
        lse_ref[0, 0] = lse  # [BQ, 1]


def _flash_fwd(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, Hkv, Tk, D]
    v: jax.Array,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    interpret: bool,
    window: int = 0,
    softcap: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = h // hkv
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    num_k = tk // bk

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=bq,
        block_k=bk,
        num_k=num_k,
        q_offset=q_offset,
        kv_offset=kv_offset,
        window=window,
        softcap=softcap,
    )
    # For causal grids, clamp the KV block index at the diagonal (and,
    # with a sliding window, from below): steps outside re-request the
    # same block, which pallas elides (no DMA), so skipped blocks cost
    # neither bandwidth nor compute.
    kv_ix = _causal_kv_clamp(causal, bq, bk, q_offset, kv_offset, num_k, window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, tq // bq, num_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, qi, ki: (b, h // group, kv_ix(qi, ki), 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, qi, ki: (b, h // group, kv_ix(qi, ki), 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _causal_kv_clamp(causal, bq, bk, q_offset, kv_offset, num_k, window=0):
    """KV block index map for (qi, ki) grids: identity when non-causal,
    else clamped to the last block intersecting q block qi's diagonal
    (and, with a sliding window, to the first block inside the window)."""
    if not causal and not window:
        return lambda qi, ki: ki

    def ix(qi, ki):
        ix = ki
        if causal:
            last = (q_offset + (qi + 1) * bq - 1 - kv_offset) // bk
            ix = jnp.minimum(ix, jnp.clip(last, 0, num_k - 1))
        if window:
            first = (q_offset + qi * bq - (window - 1) - kv_offset) // bk
            ix = jnp.maximum(ix, jnp.clip(first, 0, num_k - 1))
        return ix

    return ix


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, BK, D]
    v_ref,  # [1, 1, BK, D]
    do_ref,  # [1, 1, BQ, D]
    lse_ref,  # [1, 1, BQ, 1]
    delta_ref,  # [1, 1, BQ, 1]
    dq_ref,  # [1, 1, BQ, D]
    acc_sc,  # VMEM [BQ, D] f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_k: int,
    q_offset: int,
    kv_offset: int,
    window: int,
    softcap: float,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    q_lo = q_offset + qi * block_q
    k_lo = kv_offset + ki * block_k

    def compute():
        q = q_ref[0, 0]  # bf16 into the MXU, f32 accumulation
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # [BQ, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if softcap:
            t = jnp.tanh(s / softcap)
            s = softcap * t
        if causal or window:
            rows = q_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_lo + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            keep = rows >= cols if causal else rows == rows
            if window:
                keep = jnp.logical_and(keep, rows - cols < window)
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - jnp.where(lse <= NEG_INF / 2, 0.0, lse))
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        ds = p * (dp - delta) * scale
        if softcap:  # d(softcap·tanh(s/softcap))/ds = 1 - tanh²
            ds = ds * (1.0 - t * t)
        acc_sc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live = None
    if causal:
        live = q_lo + block_q - 1 >= k_lo
    if window:
        below = k_lo + block_k - 1 >= q_lo - (window - 1)
        live = below if live is None else jnp.logical_and(live, below)
    if live is not None:
        pl.when(live)(compute)
    else:
        compute()

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0, 0] = acc_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, BK, D]
    v_ref,  # [1, 1, BK, D]
    do_ref,  # [1, 1, BQ, D]
    lse_ref,  # [1, 1, BQ, 1]
    delta_ref,  # [1, 1, BQ, 1]
    dk_ref,  # [1, 1, BK, D]
    dv_ref,  # [1, 1, BK, D]
    dk_sc,  # VMEM [BK, D] f32
    dv_sc,  # VMEM [BK, D] f32
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_q: int,
    num_inner: int,
    q_offset: int,
    kv_offset: int,
    window: int,
    softcap: float,
):
    """dk/dv for one KV block.

    The innermost grid dim walks ``group × num_q`` — all query blocks of
    every query head in this KV head's GQA group — so the group sum
    accumulates in VMEM scratch and dk/dv come out at KV-head
    granularity directly (no [B, Hq, T, D] intermediates in HBM).
    """
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    j = pl.program_id(3)  # j = g * num_q + qi
    qi = jax.lax.rem(j, num_q)

    @pl.when(j == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    q_lo = q_offset + qi * block_q
    k_lo = kv_offset + ki * block_k

    def compute():
        q = q_ref[0, 0]  # bf16 into the MXU, f32 accumulation
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        # [BQ, 1] → [1, BQ]: columns index q rows in the transposed scores
        lse = lse_ref[0, 0].reshape(1, block_q)
        delta = delta_ref[0, 0].reshape(1, block_q)
        # transposed scores: s_t[k, q] = scale * <k_k, q_q>
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BK, BQ]
        if softcap:
            t = jnp.tanh(s_t / softcap)
            s_t = softcap * t
        if causal or window:
            rows_k = k_lo + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
            cols_q = q_lo + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
            keep = cols_q >= rows_k if causal else rows_k == rows_k
            if window:
                keep = jnp.logical_and(keep, cols_q - rows_k < window)
            s_t = jnp.where(keep, s_t, NEG_INF)
        p_t = jnp.exp(s_t - jnp.where(lse <= NEG_INF / 2, 0.0, lse))
        p_t = jnp.where(s_t <= NEG_INF / 2, 0.0, p_t)
        dv_sc[...] += jax.lax.dot_general(
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BK, BQ]
        ds_t = p_t * (dp_t - delta) * scale
        if softcap:  # d(softcap·tanh(s/softcap))/ds = 1 - tanh²
            ds_t = ds_t * (1.0 - t * t)
        dk_sc[...] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live = None
    if causal:
        live = q_lo + block_q - 1 >= k_lo
    if window:
        below = k_lo + block_k - 1 >= q_lo - (window - 1)
        live = below if live is None else jnp.logical_and(live, below)
    if live is not None:
        pl.when(live)(compute)
    else:
        compute()

    @pl.when(j == num_inner - 1)
    def _finish():
        dk_ref[0, 0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    o: jax.Array,
    lse: jax.Array,
    do: jax.Array,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    q_offset: int,
    kv_offset: int,
    interpret: bool,
    window: int = 0,
    softcap: float = 0.0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    hkv, tk = k.shape[1], k.shape[2]
    group = h // hkv
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    num_q, num_k = tq // bq, tk // bk

    # delta_i = rowsum(dO_i * O_i) — one cheap fused elementwise pass
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B, H, Tq, 1]

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        num_k=num_k, q_offset=q_offset, kv_offset=kv_offset,
        window=window, softcap=softcap,
    )
    kv_ix = _causal_kv_clamp(causal, bq, bk, q_offset, kv_offset, num_k, window)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, qi, ki: (b, h // group, kv_ix(qi, ki), 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, qi, ki: (b, h // group, kv_ix(qi, ki), 0)
            ),
            pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv directly at KV-head granularity: the inner grid dim sweeps
    # group × num_q query blocks while dk/dv accumulate in VMEM scratch.
    num_inner = group * num_q
    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        num_q=num_q, num_inner=num_inner, q_offset=q_offset, kv_offset=kv_offset,
        window=window, softcap=softcap,
    )

    def _qh(j):
        # query head for inner step j: this KV head's group member j // num_q
        return j // num_q

    if causal or window:
        # clamp the q block index into [diagonal, window end]: steps
        # outside re-request the same block (DMA elided, compute skipped)
        def _qi(ki, j):
            ix = j % num_q
            if causal:
                first = (kv_offset + ki * bk - q_offset) // bq
                ix = jnp.maximum(ix, jnp.clip(first, 0, num_q - 1))
            if window:
                # last q row that can see this KV block's newest key
                last = (
                    kv_offset + (ki + 1) * bk - 1 + (window - 1) - q_offset
                ) // bq
                ix = jnp.minimum(ix, jnp.clip(last, 0, num_q - 1))
            return ix
    else:
        def _qi(ki, j):
            return j % num_q

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, hkv, num_k, num_inner),
        in_specs=[
            pl.BlockSpec(
                (1, 1, bq, d),
                lambda b, hkv, ki, j: (b, hkv * group + _qh(j), _qi(ki, j), 0),
            ),
            pl.BlockSpec((1, 1, bk, d), lambda b, hkv, ki, j: (b, hkv, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, hkv, ki, j: (b, hkv, ki, 0)),
            pl.BlockSpec(
                (1, 1, bq, d),
                lambda b, hkv, ki, j: (b, hkv * group + _qh(j), _qi(ki, j), 0),
            ),
            pl.BlockSpec(
                (1, 1, bq, 1),
                lambda b, hkv, ki, j: (b, hkv * group + _qh(j), _qi(ki, j), 0),
            ),
            pl.BlockSpec(
                (1, 1, bq, 1),
                lambda b, hkv, ki, j: (b, hkv * group + _qh(j), _qi(ki, j), 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b, hkv, ki, j: (b, hkv, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, hkv, ki, j: (b, hkv, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11)
)
def _flash(
    q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset, interpret,
    window, softcap,
):
    o, _ = _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset,
        interpret, window, softcap,
    )
    return o


def _flash_fwd_rule(
    q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset, interpret,
    window, softcap,
):
    from jax.ad_checkpoint import checkpoint_name

    o, lse = _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset,
        interpret, window, softcap,
    )
    # Tag residuals so a rematerialized layer (llama.forward uses
    # save_only_these_names("flash_residuals")) saves them instead of
    # re-running the forward kernel inside the backward pass.
    res = checkpoint_name((q, k, v, o, lse), "flash_residuals")
    return o, res


def _flash_bwd_rule(
    causal, scale, block_q, block_k, q_offset, kv_offset, interpret,
    window, softcap, res, do,
):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(
        q, k, v, o, lse, do, causal, scale, block_q, block_k,
        q_offset, kv_offset, interpret, window, softcap,
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, Hkv, Tk, D]
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    q_offset: int = 0,
    kv_offset: int = 0,
    interpret: bool = False,
    window: int = 0,
    softcap: float = 0.0,
) -> jax.Array:
    """Differentiable flash attention (pallas, TPU).

    GQA-native: ``k``/``v`` may have fewer heads (``H % Hkv == 0``).
    ``q_offset``/``kv_offset`` give the global positions of row/col 0
    for causal masking across sequence shards (ring attention).
    ``window`` masks keys older than the sliding window (Mistral/Gemma2
    convention: key j visible to query i iff i - j < window); blocks
    entirely outside the window are skipped, so long-sequence windowed
    attention costs O(T·window) not O(T²). ``softcap`` applies the
    Gemma2 tanh score cap (with its exact gradient in the backward
    kernels).
    """
    b, h, t, d = q.shape
    assert h % k.shape[1] == 0, (h, k.shape[1])
    scale = float(scale) if scale is not None else d**-0.5
    return _flash(
        q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset,
        interpret, window, softcap,
    )


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    q_offset: int = 0,
    kv_offset: int = 0,
    interpret: bool = False,
    window: int = 0,
    softcap: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Forward-only variant returning (o, logsumexp [B, H, Tq] f32).

    Used by ring attention to merge per-shard partials; not
    differentiable directly (ring handles its own VJP).
    """
    d = q.shape[-1]
    scale = float(scale) if scale is not None else d**-0.5
    o, lse = _flash_fwd(
        q, k, v, causal, scale, block_q, block_k, q_offset, kv_offset,
        interpret, window, softcap,
    )
    return o, lse[..., 0]


def flash_supported(q: jax.Array, k: jax.Array) -> bool:
    """Whether shapes/platform allow the pallas kernel."""
    b, h, t, d = q.shape
    if jax.default_backend() != "tpu":
        return False
    return (
        d % 64 == 0
        and t % 128 == 0
        and k.shape[2] % 128 == 0
        and h % k.shape[1] == 0
    )
