"""Attention ops: pallas flash-attention TPU kernel + XLA fallback.

The hot op of every model (SURVEY.md's compute-plane requirement). The
pallas kernel streams KV blocks through VMEM with online softmax, so HBM
traffic is O(T·D) per query block instead of materializing the [T, T]
score matrix; the MXU sees [block_q, D] × [D, block_k] matmuls.
GQA is supported by mapping each Q head group onto its KV head.

Falls back to a fused-by-XLA einsum path off-TPU (CPU tests, virtual
meshes) and for shapes that don't tile (tiny test models).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _xla_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, Hkv, Tk, D]
    v: jax.Array,
    causal: bool,
    scale: float,
    q_offset: int = 0,
) -> jax.Array:
    b, h, tq, d = q.shape
    hkv = k.shape[1]
    if hkv != h:
        assert h % hkv == 0, f"GQA heads {h} not divisible by kv heads {hkv}"
        k = jnp.repeat(k, h // hkv, axis=1)
        v = jnp.repeat(v, h // hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        tk = k.shape[2]
        qi = q_offset + jnp.arange(tq)[:, None]
        kj = jnp.arange(tk)[None, :]
        s = jnp.where(qi >= kj, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int, causal: bool
):
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape[2], q_ref.shape[3]
    t = k_ref.shape[2]
    qi = pl.program_id(2)

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, D]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # only KV blocks overlapping [0, (qi+1)*BQ) contribute
        num_k = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        num_k = t // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _largest_dividing_block(t: int, cap: int, unit: int = 128) -> int:
    """Largest multiple of ``unit`` that divides ``t`` and is ≤ cap."""
    if t % unit != 0:
        raise ValueError(f"sequence length {t} must be a multiple of {unit}")
    b = min(cap - cap % unit, t)
    while b > unit and t % b != 0:
        b -= unit
    if t % b != 0:
        raise ValueError(f"no {unit}-multiple block divides T={t}")
    return b


def flash_attention(
    q: jax.Array,  # [B, H, T, D]
    k: jax.Array,  # [B, Hkv, T, D]
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    hkv = k.shape[1]
    assert h % hkv == 0
    group = h // hkv
    scale = scale if scale is not None else d**-0.5
    # Blocks must divide T exactly: a partial tail block would silently
    # drop keys (non-causal) or read out of bounds (causal).
    block_q = _largest_dividing_block(t, block_q)
    block_k = _largest_dividing_block(t, block_k)

    kernel = functools.partial(
        _flash_kernel, scale=scale, block_k=block_k, causal=causal
    )
    return pl.pallas_call(
        kernel,
        grid=(b, h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, t, d), lambda b, h, qi: (b, h // group, 0, 0)),
            pl.BlockSpec((1, 1, t, d), lambda b, h, qi: (b, h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


def _flash_ok(q: jax.Array, k: jax.Array) -> bool:
    b, h, t, d = q.shape
    if jax.default_backend() != "tpu":
        return False
    # tiling constraints: last dim 128-multiple, seq tile-aligned
    return d % 128 == 0 and t % 128 == 0 and k.shape[2] == t


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset: int = 0,
    impl: Optional[str] = None,  # None=auto | "flash" | "xla"
) -> jax.Array:
    """Dispatching attention entry point used by models."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if impl == "flash" or (impl is None and q_offset == 0 and _flash_ok(q, k)):
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return _xla_attention(q, k, v, causal=causal, scale=scale, q_offset=q_offset)
