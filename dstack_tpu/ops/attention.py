"""Attention ops: dispatching entry point (pallas flash kernel / XLA).

The hot op of every model (SURVEY.md's compute-plane requirement). The
pallas kernels live in :mod:`dstack_tpu.ops.flash` — KV-block grid with
double-buffered DMA streaming, online softmax, custom VJP with pallas
backward kernels, GQA via index_map. This module keeps the
shape/platform dispatch and the XLA fallback used off-TPU (CPU tests,
virtual meshes) and for non-tiling shapes (decode steps, tiny models).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from dstack_tpu.ops.flash import (  # re-exported public kernel API
    flash_attention,
    flash_attention_with_lse,
    flash_supported,
)

NEG_INF = -1e30

__all__ = [
    "attention",
    "flash_attention",
    "flash_attention_with_lse",
    "flash_supported",
]


def sink_softmax(s: jax.Array, sink: jax.Array) -> jax.Array:
    """Softmax over the last axis with a learned sink logit joining the
    DENOMINATOR only (gpt-oss attention sinks: an always-present column
    that absorbs probability mass and is dropped from the value sum —
    HF's concat-then-drop eager path in streaming form). ``s`` is the
    pre-masked f32 scores; ``sink`` must broadcast against ``s`` with a
    trailing singleton key axis."""
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), sink)
    e = jnp.exp(s - m)
    return e / (jnp.sum(e, axis=-1, keepdims=True) + jnp.exp(sink - m))


def _xla_attention(
    q: jax.Array,  # [B, H, Tq, D]
    k: jax.Array,  # [B, Hkv, Tk, D]
    v: jax.Array,
    causal: bool,
    scale: float,
    q_offset=0,  # int, or [B] int32 per-row offsets (packed prefill)
    window: int = 0,
    softcap: float = 0.0,
    chunk: int = 0,
    sinks: "Optional[jax.Array]" = None,  # [H] per-head sink logits
) -> jax.Array:
    b, h, tq, d = q.shape
    hkv = k.shape[1]
    if hkv != h:
        assert h % hkv == 0, f"GQA heads {h} not divisible by kv heads {hkv}"
        k = jnp.repeat(k, h // hkv, axis=1)
        v = jnp.repeat(v, h // hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if softcap:
        s = softcap * jnp.tanh(s / softcap)  # cap raw scores, then mask
    if causal or window or chunk:
        tk = k.shape[2]
        # a scalar offset broadcasts ([1, Tq, 1] rows); a [B] vector
        # gives per-row causal frontiers (packed multi-slot prefill:
        # each row's chunk sits at its own global start)
        off = jnp.reshape(jnp.asarray(q_offset, jnp.int32), (-1, 1, 1))
        qi = off + jnp.arange(tq)[None, :, None]  # [B|1, Tq, 1]
        kj = jnp.arange(tk)[None, None, :]  # [1, 1, Tk]
        keep = (
            (qi >= kj) if causal
            else jnp.ones((off.shape[0], tq, tk), bool)
        )
        if window:
            # HF sliding-window convention: key j visible to query i
            # iff 0 <= i - j < window
            keep = keep & (qi - kj < window)
        if chunk:
            # Llama4 chunked attention: key j visible to query i iff
            # both land in the same `chunk`-token block (blockwise
            # local, not a sliding window)
            keep = keep & (qi // chunk == kj // chunk)
        s = jnp.where(keep[:, None], s, NEG_INF)  # broadcast over heads
    if sinks is not None:
        p = sink_softmax(s, sinks.astype(jnp.float32).reshape(1, -1, 1, 1))
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def sink_postscale(
    o: jax.Array,  # [B, H, Tq, D] sink-less attention output
    lse: jax.Array,  # [B, H, Tq] f32 logsumexp of the same call
    sinks: jax.Array,  # [H] learned sink logits
) -> jax.Array:
    """Apply gpt-oss attention sinks AFTER a sink-less softmax.

    The sink joins the DENOMINATOR only (:func:`sink_softmax`), so the
    sinked output is an exact rescale of the sink-less one:
    ``p_sink @ v = (p @ v) · l / (l + e^{sink-m}) = o · σ(lse - sink)``
    — which lets the pallas flash kernel serve sink models without a
    sink column in the kernel (forward only: ``lse`` from
    :func:`flash_attention_with_lse` has no VJP)."""
    gate = jax.nn.sigmoid(
        lse - sinks.astype(jnp.float32).reshape(1, -1, 1)
    )[..., None]
    return (o.astype(jnp.float32) * gate).astype(o.dtype)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset=0,  # int, or [B] int32 per-row offsets
    window: int = 0,  # 0 = full attention; else sliding window size
    softcap: float = 0.0,  # 0 = off; else tanh soft-cap on scores
    chunk: int = 0,  # 0 = off; else Llama4 blockwise-chunk size
    sinks: Optional[jax.Array] = None,  # [H] gpt-oss attention sinks
    impl: Optional[str] = None,  # None=auto | "flash" | "xla"
    sinks_forward_only: bool = False,  # caller never differentiates
) -> jax.Array:
    """Dispatching attention entry point used by models.

    ``q_offset`` may be a ``[B]`` int32 vector giving each batch row its
    own causal frontier (packed multi-slot prefill: concurrent prompt
    chunks at unequal starts share one dispatch). The pallas kernel
    tiles exactly one static offset per call, so vector offsets always
    take the masked-einsum path (window/softcap/chunk/sinks included).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if isinstance(q_offset, jax.Array) and q_offset.ndim > 0:
        return _xla_attention(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            window=window, softcap=softcap, chunk=chunk, sinks=sinks,
        )
    if sinks is not None:
        # sinks join the softmax DENOMINATOR only, so a sink-less flash
        # pass rescaled by σ(lse - sink) is exact (sink_postscale) —
        # but lse has no VJP, so only forward-only callers (serving
        # prefill) may ride it; training keeps the masked XLA path
        if (
            sinks_forward_only
            and not chunk
            and (impl == "flash" or (impl is None and flash_supported(q, k)))
        ):
            o, lse = flash_attention_with_lse(
                q, k, v, causal=causal, scale=scale, q_offset=q_offset,
                window=window, softcap=softcap,
            )
            return sink_postscale(o, lse, sinks)
        return _xla_attention(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            window=window, softcap=softcap, chunk=chunk, sinks=sinks,
        )
    if chunk and causal and q_offset + q.shape[2] <= chunk:
        # all queries live in the first chunk, and causal masking
        # already hides every key past them — identical to plain
        # causal regardless of the KV buffer length (serving prefill
        # passes the full cache row), so the flash path stays eligible
        chunk = 0
    if chunk:
        # the pallas kernel has no chunk mask; blockwise-local layers
        # beyond one chunk take the masked XLA path
        return _xla_attention(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            window=window, softcap=softcap, chunk=chunk,
        )
    if impl == "flash" or (impl is None and flash_supported(q, k)):
        return flash_attention(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            window=window, softcap=softcap,
        )
    return _xla_attention(
        q, k, v, causal=causal, scale=scale, q_offset=q_offset,
        window=window, softcap=softcap,
    )
