"""Flash decode for serving: a pallas kernel for batched one-token GQA
attention over the slot KV cache.

The serving engine's decode attention is an einsum over the FULL cache
row ``[B, Hkv, Tmax, D]`` with a ``kj <= position`` mask
(serve/engine.py::decode_step) — every step streams ``Tmax`` keys per
slot from HBM regardless of how much of the row is actually written.
Decode is HBM-bandwidth-bound, so that full-width read is the cost
that grows linearly with ``max_seq`` and slot count (the bench comment
on batch 32/64 regressions).

This kernel makes the read *ragged*: per-slot ``positions`` ride the
scalar-prefetch lane, and the KV block index map clamps block indices
past a slot's length to the last live block — pallas elides the
repeated DMA (same trick as the causal clamp in ops/flash.py), so the
unwritten tail of every cache row costs neither bandwidth nor compute.
A short request in a long-context batch reads only its own prefix.

Supported in-kernel (mirroring decode_step's einsum semantics):
- GQA grouping: q arrives ``[B, Hkv, G, D]``, the cache is streamed
  once at KV width (no G× read amplification).
- int8 KV: the cache blocks load as int8 with their per-(token, head)
  f32 scales and dequantize in VMEM — HBM traffic stays int8, which is
  the entire point of ``kv_quant="int8"``.
- sliding window as a TRACED value (per-layer windows ride the
  lax.scan over layers): masked in-kernel, and leading blocks wholly
  below the window are clamp-skipped like the tail.
- tanh softcap (static), attention sinks (gpt-oss: a learned logit in
  the softmax denominator only, applied at the finish step).

Not supported (the engine falls back to the einsum path): MLA latent
caches and Llama4 chunked-attention layers.

The reference framework has no serving kernels to mirror (it is an
orchestrator, SURVEY.md §6); the GPU-world analog of this kernel is
paged/ragged decode attention in TPU serving stacks.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _decode_kernel(
    pos_ref,  # SMEM [B] int32: attend to kj <= pos[b]
    win_ref,  # SMEM [1] int32: sliding window (0 = full)
    q_ref,  # [1, 1, G, D]
    k_ref,  # [1, 1, BK, D] compute dtype or int8
    v_ref,
    *rest,  # optional (ks_ref, vs_ref [1, 1, BK] f32), optional (sink_ref [1, G] f32), then o_ref + scratch
    scale: float,
    softcap: float,
    block_k: int,
    num_k: int,
    quantized: bool,
    sinks: bool,
    rows_per_slot: int,
):
    from jax.experimental import pallas as pl

    it = iter(rest)
    ks_ref = next(it) if quantized else None
    vs_ref = next(it) if quantized else None
    sink_ref = next(it) if sinks else None
    o_ref = next(it)
    acc_sc = next(it)  # VMEM [G, D] f32
    m_sc = next(it)  # VMEM [G, 128] f32
    l_sc = next(it)  # VMEM [G, 128] f32

    b = pl.program_id(0)
    ki = pl.program_id(2)
    pos = pos_ref[b]
    win = win_ref[0]

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    # live block range for this slot (must agree with _kv_ix's clamp:
    # clamped-away blocks re-request a live block and skip compute).
    # Speculative verify (rows_per_slot = S > 1) extends the readable
    # range to the last drafted position; the window's lower bound
    # stays at row 0's (the loosest that covers every row).
    last = jnp.clip(
        (pos + rows_per_slot - 1) // block_k, 0, num_k - 1
    )
    first = jnp.where(
        win > 0, jnp.clip((pos - (win - 1)) // block_k, 0, num_k - 1), 0
    )
    live = jnp.logical_and(ki >= first, ki <= last)

    def compute():
        q = q_ref[0, 0]  # [G, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]
        if quantized:
            # per-token scales broadcast over D; HBM read was int8
            k = (k.astype(jnp.float32) * ks_ref[0, 0][:, None]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[0, 0][:, None]).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, BK] f32
        if softcap:
            s = softcap * jnp.tanh(s / softcap)
        nrows = q_ref.shape[2]
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (nrows, block_k), 1
        )
        # rows are [G, S] flattened row-major: row r verifies the
        # token at pos + (r % S), so it sees keys up to there
        qpos = pos + jax.lax.broadcasted_iota(
            jnp.int32, (nrows, block_k), 0
        ) % rows_per_slot
        keep = cols <= qpos
        keep = jnp.logical_and(
            keep, jnp.logical_or(win == 0, qpos - cols < win)
        )
        s = jnp.where(keep, s, NEG_INF)
        m_prev = m_sc[:, :1]  # [G, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(jnp.where(s <= NEG_INF / 2, NEG_INF, s) - m_safe)
        alpha = jnp.where(
            m_prev <= NEG_INF / 2, jnp.zeros_like(m_prev), jnp.exp(m_prev - m_safe)
        )
        l_sc[:, :1] = l_sc[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:, :1] = m_new

    pl.when(live)(compute)

    @pl.when(ki == num_k - 1)
    def _finish():
        m = m_sc[:, :1]
        l = l_sc[:, :1]
        acc = acc_sc[...]
        if sinks:
            # the sink joins the DENOMINATOR only (ops/attention.py::
            # sink_softmax): rescale running stats to max(m, sink)
            snk = sink_ref[0][:, None].astype(jnp.float32)  # [G, 1]
            m_f = jnp.maximum(m, snk)
            alpha = jnp.where(
                m <= NEG_INF / 2, jnp.zeros_like(m), jnp.exp(m - m_f)
            )
            l = l * alpha + jnp.exp(snk - m_f)
            acc = acc * alpha
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)


def flash_decode(
    q: jax.Array,  # [B, Hkv, G, D] compute dtype
    k: jax.Array,  # [B, Hkv, T, D] compute dtype, or int8 with k_scale
    v: jax.Array,
    positions: jax.Array,  # [B] int32: attend to kj <= positions[b]
    *,
    scale: float,
    window: Optional[jax.Array] = None,  # traced int32 scalar; None/0 = full
    softcap: float = 0.0,
    sinks: Optional[jax.Array] = None,  # [Hkv, G] sink logits
    k_scale: Optional[jax.Array] = None,  # [B, Hkv, T] f32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
    block_k: int = 512,
    interpret: bool = False,
    rows_per_slot: int = 1,
) -> jax.Array:
    """One-token-per-slot GQA attention over the cache → [B, Hkv, G, D].

    Ragged: each slot reads only the KV blocks covering
    ``positions[b]`` (and, with a window, only blocks inside it).

    ``rows_per_slot=S`` serves speculative verify: ``q``'s row axis is
    ``[G, S]`` flattened row-major, row ``g*S + s`` attends to keys
    ``<= positions[b] + s`` (the engine scatters the S candidate K/V
    into the cache before calling). ``sinks`` must then be pre-expanded
    to ``[Hkv, G*S]``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hkv, g, d = q.shape
    t = k.shape[2]
    if t % 128:
        raise ValueError(
            f"flash_decode: cache length {t} must be a multiple of 128 "
            "(gate callers with flash_decode_supported)"
        )
    quantized = k_scale is not None
    bk = min(block_k, t)
    while t % bk:
        bk -= 128
    num_k = t // bk

    if window is None:
        window = jnp.zeros((), jnp.int32)
    win_arr = jnp.asarray(window, jnp.int32).reshape(1)
    pos_arr = positions.astype(jnp.int32)

    def _kv_ix(bi, h, ki, pos_ref, win_ref):
        # must agree with the kernel's `live` range: tail blocks clamp
        # to the last live block, leading out-of-window blocks to the
        # first — re-requested blocks cost no DMA
        last = jnp.clip(
            (pos_ref[bi] + rows_per_slot - 1) // bk, 0, num_k - 1
        )
        ix = jnp.minimum(ki, last)
        first = jnp.where(
            win_ref[0] > 0,
            jnp.clip((pos_ref[bi] - (win_ref[0] - 1)) // bk, 0, num_k - 1),
            0,
        )
        return (bi, h, jnp.maximum(ix, first), 0)

    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda bi, h, ki, p, w: (bi, h, 0, 0)),
        pl.BlockSpec((1, 1, bk, d), _kv_ix),
        pl.BlockSpec((1, 1, bk, d), _kv_ix),
    ]
    args = [q, k, v]
    if quantized:
        sc_ix = lambda bi, h, ki, p, w: _kv_ix(bi, h, ki, p, w)[:3]
        in_specs += [
            pl.BlockSpec((1, 1, bk), sc_ix),
            pl.BlockSpec((1, 1, bk), sc_ix),
        ]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    if sinks is not None:
        in_specs.append(
            pl.BlockSpec((1, g), lambda bi, h, ki, p, w: (h, 0))
        )
        args.append(sinks.astype(jnp.float32))

    kernel = functools.partial(
        _decode_kernel,
        scale=scale,
        softcap=softcap,
        block_k=bk,
        num_k=num_k,
        quantized=quantized,
        sinks=sinks is not None,
        rows_per_slot=rows_per_slot,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, num_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bi, h, ki, p, w: (bi, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((g, d), jnp.float32),
            pltpu.VMEM((g, 128), jnp.float32),
            pltpu.VMEM((g, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(pos_arr, win_arr, *args)


def flash_decode_supported(config, max_seq: int) -> bool:
    """Whether the engine may route decode attention through the
    kernel for this model/cache shape (the caller still falls back
    per-call when ``interpret`` isn't wanted off-TPU)."""
    return (
        not config.mla
        and not config.attention_chunk_size
        and config.head_dim % 64 == 0
        and max_seq % 128 == 0
        and config.n_heads % config.n_kv_heads == 0
    )
