"""Per-service request statistics for the RPS autoscaler.

Parity: reference gateway/services/stats.py:156 (RPS windows from nginx
access logs) — here the in-server proxy records requests directly.
"""

import time
from collections import defaultdict, deque
from typing import Deque


class ServiceStats:
    def __init__(self, window_seconds: float = 600.0):
        self.window = window_seconds
        self._requests: dict[tuple[str, str], Deque[float]] = defaultdict(deque)
        # gateway-reported windows: (project, run) -> (rps, recorded_monotonic)
        self._external: dict[tuple[str, str], tuple[float, float]] = {}

    def record(self, project: str, run_name: str) -> None:
        q = self._requests[(project, run_name)]
        q.append(time.monotonic())
        self._trim(q)

    def _trim(self, q: Deque[float]) -> None:
        cutoff = time.monotonic() - self.window
        while q and q[0] < cutoff:
            q.popleft()

    def merge_external(self, project: str, run_name: str, rps: float) -> None:
        """Record a gateway-scraped RPS sample (reference: server pulls
        gateway /api/stats windows to drive the autoscaler)."""
        self._external[(project, run_name)] = (rps, time.monotonic())

    def rps(self, project: str, run_name: str, over_seconds: float = 60.0) -> float:
        # policy: max, not sum, of the gateway-scraped window and the
        # locally recorded requests — deliberately conservative
        # de-duplication (relay topologies can report the same requests
        # through both channels; mixed split-ingress traffic is instead
        # under-counted, the cheaper autoscaling error)
        local = 0.0
        external = 0.0
        ext = self._external.get((project, run_name))
        if ext is not None and time.monotonic() - ext[1] < 120.0:
            external = ext[0]
        q = self._requests.get((project, run_name))
        if q:
            self._trim(q)
            cutoff = time.monotonic() - over_seconds
            local = sum(1 for t in q if t >= cutoff) / over_seconds
        return max(local, external)

    def snapshot(
        self,
        project: str,
        run_name: str,
        buckets: int = 20,
        bucket_seconds: float = 30.0,
    ) -> tuple[float, list[float]]:
        """(rps over 60s, per-bucket RPS oldest-first) in ONE pass over
        the request deque — /services/list calls this per poll, and a
        busy service retains tens of thousands of timestamps. The
        latest gateway-scraped window, if fresh, joins both numbers (on
        the last bucket) so gateway-routed services do not chart flat
        zero."""
        now = time.monotonic()
        out = [0.0] * buckets
        recent = 0
        q = self._requests.get((project, run_name))
        if q:
            self._trim(q)
            span = buckets * bucket_seconds
            for t in q:
                age = now - t
                if age < 60.0:
                    recent += 1
                if age < span:
                    out[buckets - 1 - int(age // bucket_seconds)] += 1
            out = [c / bucket_seconds for c in out]
        rps60 = recent / 60.0
        ext = self._external.get((project, run_name))
        if ext is not None and now - ext[1] < 120.0:
            # same max-not-sum policy as rps(): both sources watched
            # the same requests when both are live
            out[-1] = max(out[-1], ext[0])
            rps60 = max(rps60, ext[0])
        return round(rps60, 3), [round(v, 3) for v in out]

    def last_request_at(self, project: str, run_name: str) -> float:
        q = self._requests.get((project, run_name))
        return q[-1] if q else 0.0


_stats = ServiceStats()


def get_service_stats() -> ServiceStats:
    return _stats
