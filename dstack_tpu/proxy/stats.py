"""Per-service request statistics for the RPS autoscaler.

Parity: reference gateway/services/stats.py:156 (RPS windows from nginx
access logs) — here the in-server proxy records requests directly.
"""

import time
from collections import defaultdict, deque
from typing import Deque


class ServiceStats:
    def __init__(self, window_seconds: float = 600.0):
        self.window = window_seconds
        self._requests: dict[tuple[str, str], Deque[float]] = defaultdict(deque)
        # gateway-reported windows: (project, run) -> (rps, recorded_monotonic)
        self._external: dict[tuple[str, str], tuple[float, float]] = {}

    def record(self, project: str, run_name: str) -> None:
        q = self._requests[(project, run_name)]
        q.append(time.monotonic())
        self._trim(q)

    def _trim(self, q: Deque[float]) -> None:
        cutoff = time.monotonic() - self.window
        while q and q[0] < cutoff:
            q.popleft()

    def merge_external(self, project: str, run_name: str, rps: float) -> None:
        """Record a gateway-scraped RPS sample (reference: server pulls
        gateway /api/stats windows to drive the autoscaler)."""
        self._external[(project, run_name)] = (rps, time.monotonic())

    def rps(self, project: str, run_name: str, over_seconds: float = 60.0) -> float:
        total = 0.0
        ext = self._external.get((project, run_name))
        if ext is not None and time.monotonic() - ext[1] < 120.0:
            total += ext[0]
        q = self._requests.get((project, run_name))
        if q:
            self._trim(q)
            cutoff = time.monotonic() - over_seconds
            total += sum(1 for t in q if t >= cutoff) / over_seconds
        return total

    def last_request_at(self, project: str, run_name: str) -> float:
        q = self._requests.get((project, run_name))
        return q[-1] if q else 0.0


_stats = ServiceStats()


def get_service_stats() -> ServiceStats:
    return _stats
