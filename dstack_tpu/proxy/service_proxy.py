"""In-server service proxy + OpenAI-compatible model gateway.

Parity: reference server/services/proxy (``/proxy/services/{proj}/{run}/``
gateway-less ingress, service_proxy.py:135) and the model adapter
(reference proxy/lib/routers/model_proxy.py:102, clients/openai.py:67 /
tgi.py:208). Requests resolve the run's RUNNING service replicas into
the shared routing pool (``dstack_tpu.routing``): picks are
least-outstanding over probed replica health, connect errors and 5xx
fail over to another replica, and each request is recorded for the
autoscaler.
"""

import json
from typing import Optional

import aiohttp
from aiohttp import web

from dstack_tpu import qos
from dstack_tpu.core.models.runs import JobProvisioningData, JobStatus
from dstack_tpu.obs import tracing
from dstack_tpu.proxy.stats import get_service_stats
from dstack_tpu.qos.web import admit_or_shed
from dstack_tpu.routing import forward_with_failover, get_pool_registry
from dstack_tpu.server.db import Database, loads
from dstack_tpu.utils.logging import get_logger

logger = get_logger("proxy.service")


def _request_tenant(user_row: Optional[dict]) -> str:
    """The QoS bucket key for one proxied request: the authenticated
    username when the proxy resolved one, else the shared anonymous
    tenant. Never a client-supplied header, and never a digest of an
    UNVERIFIED Bearer token (``auth: false`` services skip token
    validation): an attacker rotating made-up tokens would mint a
    fresh full-burst bucket per token — a budget bypass — and churn
    the bounded tenant map. No verified identity ⇒ one shared
    budget."""
    if user_row is not None:
        return str(user_row["username"])[:64]
    return qos.ANONYMOUS_TENANT


async def _resolve_replicas(
    db: Database, project_name: str, run_name: str
) -> list[tuple[str, str, int]]:
    """→ [(job_id, host, port)] of RUNNING service replicas."""
    project = await db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if project is None:
        return []
    run = await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project["id"], run_name),
    )
    if run is None:
        return []
    jobs = await db.fetchall(
        "SELECT * FROM jobs WHERE run_id = ? AND status = ?",
        (run["id"], JobStatus.RUNNING.value),
    )
    out = []
    for job in jobs:
        jpd_raw = loads(job.get("job_provisioning_data"))
        spec = loads(job["job_spec"])
        if jpd_raw is None or spec.get("service_port") is None:
            continue
        jpd = JobProvisioningData.model_validate(jpd_raw)
        # host networking: service listens on its container port on the host
        out.append(
            (job["id"], jpd.hostname or "127.0.0.1", int(spec["service_port"]))
        )
    return out


async def _synced_pool(db: Database, project: str, run_name: str):
    """Resolve RUNNING replicas and reconcile them into the shared
    routing pool (health state survives across requests; membership is
    authoritative from the DB every time)."""
    replicas = await _resolve_replicas(db, project, run_name)
    pool = get_pool_registry().pool(project, run_name)
    pool.sync(replicas)
    return pool


def _proxy_session(app: web.Application) -> aiohttp.ClientSession:
    """One long-lived pooled session for the proxy hot path."""
    state = app["state"]
    session = state.get("proxy_session")
    if session is None or session.closed:
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=300),
            connector=aiohttp.TCPConnector(limit=256, keepalive_timeout=30),
        )
        state["proxy_session"] = session
    return session


async def _bearer_user(request: web.Request, db: Database):
    """The user row for the request's Bearer token, or None (single
    token-parsing path for every proxy auth decision)."""
    auth = request.headers.get("Authorization", "")
    token = (
        auth.removeprefix("Bearer ").strip()
        if auth.startswith("Bearer ")
        else ""
    )
    if not token:
        return None
    from dstack_tpu.server.services.users import get_user_by_token

    return await get_user_by_token(db, token)


async def _check_service_auth(
    request: web.Request, db: Database, run_row: Optional[dict], conf: dict
) -> tuple:
    """Enforce the service's ``auth: true`` (the default): the caller must
    present a valid server token (reference: gateway auth check against
    /api/auth). Returns ``(error response or None, resolved user row or
    None)`` — the user row doubles as the QoS tenant identity. An
    ``auth: false`` service skips the token DB lookup entirely (the old
    fast path): with no verified identity its QoS tenant is the shared
    anonymous one (see ``_request_tenant``)."""
    if run_row is None:
        return None, None  # nonexistent run: fall through to 503 (no info leak)
    if conf.get("auth") is False:
        return None, None
    user = await _bearer_user(request, db)
    if user is not None:
        return None, user
    return (
        web.json_response(
            {"detail": "authentication required for this service"}, status=401
        ),
        None,
    )


async def _get_run_row(db: Database, project_name: str, run_name: str) -> Optional[dict]:
    project = await db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if project is None:
        return None
    return await db.fetchone(
        "SELECT * FROM runs WHERE project_id = ? AND run_name = ? AND deleted = 0",
        (project["id"], run_name),
    )


async def service_proxy_handler(request: web.Request) -> web.StreamResponse:
    db: Database = request.app["state"]["db"]
    project = request.match_info["project_name"]
    run_name = request.match_info["run_name"]
    path = request.match_info.get("path", "")
    run_row = await _get_run_row(db, project, run_name)
    conf = (
        (loads(run_row["run_spec"]) or {}).get("configuration", {})
        if run_row is not None
        else {}
    )
    denied, user = await _check_service_auth(request, db, run_row, conf)
    if denied is not None:
        return denied
    tenant = _request_tenant(user)
    if run_row is not None:  # no stats/bucket keys from random run names
        shed = admit_or_shed(
            conf.get("qos"), tenant, project, run_name,
            span=request.get(tracing.REQUEST_SPAN_KEY),
        )
        if shed is not None:
            return shed
    # record BEFORE the no-replica check: demand on a scaled-to-zero
    # service is what makes the autoscaler scale it back up — but only
    # for runs that actually exist (no unbounded keys from random names)
    if run_row is not None:
        get_service_stats().record(project, run_name)
    pool = await _synced_pool(db, project, run_name)
    if pool.size() == 0:
        return web.json_response(
            {"detail": f"no running replicas for {run_name}"},
            status=503,
            headers={"Retry-After": str(pool.retry_after_hint())},
        )
    return await forward_with_failover(
        request, pool, _proxy_session(request.app), path,
        extra_headers={qos.TENANT_HEADER: tenant},
    )


async def model_proxy_handler(request: web.Request) -> web.StreamResponse:
    """OpenAI-compatible endpoint: routes by ``model`` name to the
    service whose config registered that model."""
    db: Database = request.app["state"]["db"]
    project = request.match_info["project_name"]
    path = request.match_info.get("path", "chat/completions")
    body_raw = await request.read()
    try:
        payload = json.loads(body_raw) if body_raw else {}
    except json.JSONDecodeError:
        return web.json_response({"detail": "invalid JSON"}, status=400)
    model_name = payload.get("model")
    run_row = await _find_model_service(db, project, model_name)
    if run_row is None:
        return web.json_response(
            {"detail": f"model {model_name!r} not found"}, status=404
        )
    run_name = run_row["run_name"]
    conf = (loads(run_row["run_spec"]) or {}).get("configuration", {})
    denied, user = await _check_service_auth(request, db, run_row, conf)
    if denied is not None:
        return denied
    tenant = _request_tenant(user)
    shed = admit_or_shed(
        conf.get("qos"), tenant, project, run_name,
        span=request.get(tracing.REQUEST_SPAN_KEY),
    )
    if shed is not None:
        return shed
    get_service_stats().record(project, run_name)  # before the 503 check
    pool = await _synced_pool(db, project, run_name)
    if pool.size() == 0:
        return web.json_response(
            {"detail": f"no running replicas for model {model_name}"},
            status=503,
            headers={"Retry-After": str(pool.retry_after_hint())},
        )
    model_conf = conf.get("model", {}) or {}
    if model_conf.get("format") == "tgi":
        # the TGI adapter drives its own upstream exchange (SSE
        # re-framing): pick one healthy replica, no mid-protocol retries
        entry = pool.pick()
        if entry is None:
            return web.json_response(
                {"detail": f"no healthy replicas for model {model_name}"},
                status=503,
                headers={"Retry-After": str(pool.retry_after_hint())},
            )
        pool.acquire(entry)
        try:
            resp = await _tgi_chat_completions(
                request, payload, entry.host, entry.port, path, model_conf
            )
        except Exception:
            pool.report_failure(entry)
            raise
        else:
            if resp.status < 500:
                pool.report_success(entry)
            else:
                pool.report_failure(entry)
            return resp
        finally:
            pool.release(entry)
    prefix = model_conf.get("prefix", "/v1")
    return await forward_with_failover(
        request,
        pool,
        _proxy_session(request.app),
        f"{prefix.strip('/')}/{path.lstrip('/')}",
        extra_headers={qos.TENANT_HEADER: tenant},
    )


async def _tgi_chat_completions(
    request: web.Request,
    payload: dict,
    host: str,
    port: int,
    path: str,
    model_conf: dict,
) -> web.StreamResponse:
    """OpenAI chat/completions adapted onto a TGI replica
    (proxy/model_tgi.py; parity: reference clients/tgi.py:208)."""
    from dstack_tpu.proxy import model_tgi

    if path.removeprefix("v1/") != "chat/completions":
        return web.json_response(
            {"detail": f"TGI-format models only serve chat/completions, not {path!r}"},
            status=404,
        )
    model_name = model_conf.get("name", "")
    eos = model_conf.get("eos_token") or model_tgi.DEFAULT_EOS_TOKEN
    try:
        tgi_payload = model_tgi.openai_to_tgi(
            payload, model_conf.get("chat_template"), eos
        )
    except model_tgi.TGIAdapterError as e:
        return web.json_response({"detail": str(e)}, status=e.status)
    # TGI serves /generate at the root; an explicit non-default prefix is
    # honored for replicas behind their own sub-path
    prefix = (model_conf.get("prefix") or "").strip("/")
    if prefix == "v1":
        prefix = ""
    base = f"http://{host}:{port}/" + (f"{prefix}/" if prefix else "")
    session = _proxy_session(request.app)
    stream = bool(payload.get("stream"))
    try:
        if not stream:
            async with session.post(
                f"{base}generate", json=tgi_payload
            ) as resp:
                body = await resp.read()
                if resp.status != 200:
                    return web.json_response(
                        {"detail": body.decode(errors="replace")}, status=resp.status
                    )
                data = json.loads(body)
                out = model_tgi.tgi_to_openai(
                    data, model_name, tgi_payload["parameters"]["stop"]
                )
                return web.json_response(out)
        import time as _time
        import uuid as _uuid

        completion_id = f"chatcmpl-{_uuid.uuid4().hex}"
        created = int(_time.time())
        # connect to the replica BEFORE committing SSE headers: a down
        # replica must surface as a plain 502, not a corrupted stream
        resp = await session.post(f"{base}generate_stream", json=tgi_payload)
        try:
            if resp.status != 200:
                err = await resp.read()
                return web.json_response(
                    {"detail": err.decode(errors="replace")}, status=resp.status
                )
            out_resp = web.StreamResponse(
                status=200,
                headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                },
            )
            await out_resp.prepare(request)
            try:
                async for event in model_tgi.iter_sse_data(resp):
                    try:
                        chunk = model_tgi.tgi_chunk_to_openai(
                            event, model_name, completion_id, created
                        )
                    except model_tgi.TGIAdapterError as e:
                        await out_resp.write(
                            b"data: "
                            + json.dumps({"error": str(e)}).encode()
                            + b"\n\n"
                        )
                        break
                    await out_resp.write(
                        b"data: " + json.dumps(chunk).encode() + b"\n\n"
                    )
            except aiohttp.ClientError as e:
                # replica died mid-stream: headers are committed, so
                # report in-band as an SSE error event
                await out_resp.write(
                    b"data: " + json.dumps({"error": repr(e)}).encode() + b"\n\n"
                )
            await out_resp.write(b"data: [DONE]\n\n")
            return out_resp
        finally:
            resp.release()
    except aiohttp.ClientError as e:
        return web.json_response(
            {"detail": f"error requesting TGI replica: {e!r}"}, status=502
        )


async def model_list_handler(request: web.Request) -> web.Response:
    db: Database = request.app["state"]["db"]
    project = request.match_info["project_name"]
    # same policy as the gateway's catalog (gateway/app.py model_list):
    # anonymous callers see only `auth: false` (public) models; a valid
    # server token reveals the rest — model names of private services
    # are deployment metadata, not enumerable anonymously
    authed = await _bearer_user(request, db) is not None
    rows = await _list_model_services(db, project)
    data = []
    for r in rows:
        conf = loads(r["run_spec"])["configuration"]
        if not authed and conf.get("auth") is not False:
            continue
        data.append({
            "id": (conf["model"] or {}).get("name"),
            "object": "model",
            "owned_by": "dstack-tpu",
        })
    return web.json_response({"object": "list", "data": data})


async def _list_model_services(db: Database, project_name: str) -> list[dict]:
    project = await db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if project is None:
        return []
    rows = await db.fetchall(
        "SELECT * FROM runs WHERE project_id = ? AND deleted = 0 "
        "AND status IN ('running','provisioning','submitted')",
        (project["id"],),
    )
    out = []
    for r in rows:
        conf = (loads(r["run_spec"]) or {}).get("configuration", {})
        if conf.get("type") == "service" and conf.get("model"):
            out.append(r)
    return out


async def _find_model_service(
    db: Database, project_name: str, model_name: Optional[str]
) -> Optional[dict]:
    if model_name is None:
        return None
    for r in await _list_model_services(db, project_name):
        conf = loads(r["run_spec"])["configuration"]
        if (conf.get("model") or {}).get("name") == model_name:
            return r
    return None


def register_routes(app: web.Application) -> None:
    app.router.add_route(
        "*",
        "/proxy/services/{project_name}/{run_name}/{path:.*}",
        service_proxy_handler,
    )
    app.router.add_get(
        "/proxy/models/{project_name}/models", model_list_handler
    )
    app.router.add_post(
        "/proxy/models/{project_name}/{path:.*}", model_proxy_handler
    )


def service_url(project_name: str, run_name: str) -> str:
    return f"/proxy/services/{project_name}/{run_name}/"
