"""TGI → OpenAI model-format adapter.

Services declaring ``model: {name: …, format: tgi}`` speak the
text-generation-inference REST API (``/generate``, ``/generate_stream``)
but are exposed through the gateway's OpenAI-compatible
``/proxy/models/{project}/chat/completions`` endpoint. This module
renders the chat template, maps OpenAI sampling params onto TGI
parameters, and converts responses (incl. SSE streams) back to OpenAI
chat-completion objects. Parity: reference
proxy/lib/services/model_proxy/clients/tgi.py:208 (httpx+jinja there;
aiohttp here, same wire behavior).
"""

import json
import time
import uuid
from typing import AsyncIterator, Optional

import jinja2
import jinja2.sandbox

from dstack_tpu.utils.logging import get_logger

logger = get_logger("proxy.model_tgi")

# Llama-3-style default; services can override with model.chat_template
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|start_header_id|>{{ message['role'] }}<|end_header_id|>\n\n"
    "{{ message['content'] or '' }}"
    "{% if message.get('tool_calls') %}{{ message['tool_calls'] | tojson }}"
    "{% endif %}<|eot_id|>"
    "{% endfor %}"
    "{% if add_generation_prompt %}"
    "<|start_header_id|>assistant<|end_header_id|>\n\n"
    "{% endif %}"
)
DEFAULT_EOS_TOKEN = "<|eot_id|>"


class TGIAdapterError(Exception):
    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = status


def render_chat(
    messages: list,
    chat_template: Optional[str] = None,
    tools: Optional[list] = None,
) -> str:
    """Messages → prompt via a sandboxed jinja chat template.

    ``tools`` (OpenAI function specs) are exposed to the template like
    HF ``apply_chat_template(tools=...)`` — tool-capable templates
    (llama3.1/qwen/mistral) render them into their system prompt;
    others ignore the variable.
    """
    env = jinja2.sandbox.ImmutableSandboxedEnvironment(
        trim_blocks=True, lstrip_blocks=True
    )

    def _raise(message: str):
        raise jinja2.TemplateError(message)

    env.globals["raise_exception"] = _raise
    try:
        template = env.from_string(chat_template or DEFAULT_CHAT_TEMPLATE)
        return template.render(
            messages=messages, tools=tools, add_generation_prompt=True
        )
    except jinja2.TemplateError as e:
        raise TGIAdapterError(f"chat template failed: {e}")


def openai_to_tgi(payload: dict, chat_template: Optional[str], eos_token: str) -> dict:
    """OpenAI chat/completions request → TGI /generate payload."""
    messages = payload.get("messages")
    if not isinstance(messages, list) or not messages:
        raise TGIAdapterError("'messages' is required")
    inputs = render_chat(messages, chat_template, tools=payload.get("tools"))
    stop = payload.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    stop = list(stop)
    if eos_token and eos_token not in stop:
        stop.append(eos_token)
    parameters = {
        "do_sample": True,
        "max_new_tokens": payload.get("max_tokens") or 512,
        "stop": stop,
        "details": True,
        "decoder_input_details": not payload.get("stream", False),
    }
    if payload.get("seed") is not None:
        parameters["seed"] = payload["seed"]
    if payload.get("temperature") is not None:
        parameters["temperature"] = payload["temperature"]
    if payload.get("n"):
        parameters["best_of"] = payload["n"]
    top_p = payload.get("top_p")
    if top_p is not None and top_p < 1.0:
        parameters["top_p"] = top_p
    return {"inputs": inputs, "parameters": parameters}


def _finish_reason(reason: str) -> str:
    if reason in ("stop_sequence", "eos_token"):
        return "stop"
    return "length" if reason == "length" else reason


def _trim_stop(text: str, stop: list) -> str:
    for s in stop:
        if s and text.endswith(s):
            return text[: -len(s)]
    return text


def tgi_to_openai(data: dict, model: str, stop: list) -> dict:
    """TGI /generate response → OpenAI chat.completion object."""
    details = data.get("details") or {}
    choices = [
        {
            "index": 0,
            "message": {
                "role": "assistant",
                "content": _trim_stop(data.get("generated_text", ""), stop),
            },
            "finish_reason": _finish_reason(details.get("finish_reason", "stop")),
        }
    ]
    completion_tokens = details.get("generated_tokens", 0)
    prompt_tokens = len(details.get("prefill", []))
    for i, seq in enumerate(details.get("best_of_sequences", []), start=1):
        choices.append(
            {
                "index": i,
                "message": {
                    "role": "assistant",
                    "content": _trim_stop(seq.get("generated_text", ""), stop),
                },
                "finish_reason": _finish_reason(seq.get("finish_reason", "stop")),
            }
        )
        completion_tokens += seq.get("generated_tokens", 0)
    return {
        "id": f"chatcmpl-{uuid.uuid4().hex}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens,
        },
    }


def tgi_chunk_to_openai(
    data: dict, model: str, completion_id: str, created: int
) -> dict:
    """One TGI SSE stream event → OpenAI chat.completion.chunk."""
    if "error" in data:
        raise TGIAdapterError(str(data["error"]), status=502)
    if data.get("details") is not None:
        choices = [
            {
                "index": 0,
                "delta": {},
                "finish_reason": _finish_reason(
                    data["details"].get("finish_reason", "stop")
                ),
            }
        ]
    else:
        choices = [
            {
                "index": 0,
                "delta": {
                    "role": "assistant",
                    "content": (data.get("token") or {}).get("text", ""),
                },
                "finish_reason": None,
            }
        ]
    return {
        "id": completion_id,
        "object": "chat.completion.chunk",
        "created": created,
        "model": model,
        "choices": choices,
    }


async def iter_sse_data(resp) -> AsyncIterator[dict]:
    """Yield decoded ``data: {json}`` events from an aiohttp response."""
    buf = b""
    async for chunk, _ in resp.content.iter_chunks():
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            text = line.decode(errors="replace").strip()
            if text.startswith("data:"):
                body = text[len("data:"):].strip()
                if body and body != "[DONE]":
                    yield json.loads(body)
