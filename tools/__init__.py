"""Repo tooling package (``python -m tools.dtpu_lint`` etc.)."""
