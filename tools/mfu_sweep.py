"""MFU sweep: find the best single-chip train-step configuration fast.

The axon TPU tunnel comes and goes; when it is up, minutes count. This
sweep measures tokens/s/chip + MFU for a grid of (batch, seq,
loss_impl, remat) on the flagship model in ONE session, prints a table,
and names the winner — the numbers `bench.py` should then pin.

Usage:
  python tools/mfu_sweep.py                       # flagship on TPU
  python tools/mfu_sweep.py --model llama-tiny --platform cpu --quick
"""

import argparse
import dataclasses
import itertools
import json
import os
import statistics
import sys
import time

# runnable as `python tools/mfu_sweep.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _tpu_reachable(timeout: float = 90.0) -> bool:
    from dstack_tpu.utils.tpu_probe import tpu_reachable  # one impl

    return tpu_reachable(timeout=timeout)


def measure(config, batch, seq, loss_impl, remat, steps, peak_flops):
    import jax
    import jax.numpy as jnp

    from dstack_tpu.parallel.mesh import MeshConfig, make_mesh
    from dstack_tpu.train.step import (
        default_optimizer,
        flops_per_token,
        make_train_step,
        sharded_init,
    )

    cfg = dataclasses.replace(config, remat=remat)
    mesh = make_mesh(
        MeshConfig(dp=1, fsdp=1, sp=1, tp=1), devices=jax.devices()[:1]
    )
    opt = default_optimizer(lr=1e-4)
    state, _ = sharded_init(cfg, opt, mesh, seed=0)
    step_fn = make_train_step(cfg, opt, mesh, loss_impl=loss_impl)
    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0, cfg.vocab_size)
    data = {
        "tokens": tokens,
        "targets": jnp.roll(tokens, -1, axis=1),
        "mask": jnp.ones_like(tokens),
    }

    def sync(x):
        jax.block_until_ready(x)
        return float(jax.device_get(x))

    t_compile = time.perf_counter()
    state, m = step_fn(state, data)
    sync(m["loss"])
    compile_s = time.perf_counter() - t_compile
    state, m = step_fn(state, data)
    sync(m["loss"])
    inner = 1 if steps <= 3 else 5
    times = []
    for _ in range(max(steps // inner, 3)):
        t0 = time.perf_counter()
        for _ in range(inner):
            state, m = step_fn(state, data)
        sync(m["loss"])
        times.append((time.perf_counter() - t0) / inner)
    dt = statistics.median(times)
    tps = batch * seq / dt
    mfu = tps * flops_per_token(cfg, seq) / peak_flops
    # free everything before the next grid point
    del state, m, data, step_fn, opt
    jax.clear_caches()
    return {
        "batch": batch, "seq": seq, "loss_impl": loss_impl, "remat": remat,
        "tok_s": round(tps, 1), "mfu": round(mfu, 4),
        "step_s": round(dt, 4), "compile_s": round(compile_s, 1),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default=None, help="default: flagship on TPU, tiny on CPU")
    p.add_argument("--platform", default=None)
    p.add_argument("--quick", action="store_true")
    p.add_argument("--batches", default=None, help="comma list, e.g. 4,8,16")
    p.add_argument("--seqs", default=None)
    p.add_argument(
        "--peak-flops", type=float, default=None,
        help="default: 197e12 (v5e bf16) on TPU, 1e12 nominal on CPU",
    )
    args = p.parse_args()

    if args.platform is None and not _tpu_reachable():
        print(json.dumps({"error": "TPU unreachable (tunnel down); pass --platform cpu for a smoke run"}))
        return 1

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from dstack_tpu.models import llama

    on_tpu = jax.default_backend() in ("tpu", "axon")
    model = args.model or ("llama-3.2-1b" if on_tpu else "llama-tiny")
    config = llama.CONFIGS[model]
    peak = args.peak_flops or (197e12 if on_tpu else 1e12)
    if on_tpu:
        batches = [int(x) for x in (args.batches or "4,8,16").split(",")]
        seqs = [int(x) for x in (args.seqs or "1024,2048").split(",")]
        steps = 10 if args.quick else 20
        grid = [
            (b, s, li, rm)
            for (b, s), li, rm in itertools.product(
                itertools.product(batches, seqs),
                ("fused", "chunked"),
                (True, False),
            )
        ]
    else:
        batches = [int(x) for x in (args.batches or "4").split(",")]
        seqs = [int(x) for x in (args.seqs or "128").split(",")]
        steps = 3
        grid = [(batches[0], seqs[0], "fused", True), (batches[0], seqs[0], "chunked", False)]

    results = []
    for b, s, li, rm in grid:
        try:
            r = measure(config, b, s, li, rm, steps, peak)
        except Exception as e:  # OOM configs report and move on
            r = {
                "batch": b, "seq": s, "loss_impl": li, "remat": rm,
                "error": f"{type(e).__name__}: {str(e)[:120]}",
            }
        results.append(r)
        print(json.dumps(r), flush=True)

    ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print(json.dumps({"best": best, "model": model}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
