"""Control-plane capacity benchmark.

The reference documents its per-replica capacity as ~150 active
jobs/runs/instances with <= 2 min processing latency and a 75 jobs/min
scheduling ceiling (reference server/background/__init__.py:45-56).
This tool measures the same two numbers for THIS control plane:

1. **Scheduling ramp**: N runs submitted at once -> time for every job
   to reach RUNNING through the real reconcilers (jobs/min).
2. **Steady-state visit latency**: with N RUNNING jobs (+ their
   instances) the reconcilers keep polling agents; we record every
   per-job visit and report the p50/p95/max gap between consecutive
   visits of the same job. Target: max <= 120 s.

Compute + on-host agents are faked (5 ms simulated RTT per call) so the
measurement isolates the control plane: DB, locking, reconciler
batching. Engines: sqlite in-memory (default), ``--db pgwire`` (the
bundled wire-protocol fake Postgres), or ``--db postgres`` with
``DTPU_TEST_PG_DSN``.

Usage::

    python tools/capacity_bench.py --jobs 150 --window 60
"""

import argparse
import asyncio
import contextlib
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

AGENT_RTT_S = 0.005  # simulated server<->agent round trip


def _fake_agents():
    """(shim_client_for, runner_client_for) replacements with canned
    happy-path responses and a small simulated RTT."""
    from contextlib import asynccontextmanager

    from dstack_tpu.agent import schemas as agent_schemas

    class FakeShim:
        async def healthcheck(self):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.HealthcheckResponse(
                service="tpu-shim", version="bench"
            )

        async def submit_task(self, req):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.TaskInfo(
                id=req.id,
                status=agent_schemas.TaskStatus.PULLING,
                ports=[agent_schemas.PortMapping(container_port=10999, host_port=10999)],
            )

        async def get_task(self, task_id):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.TaskInfo(
                id=task_id,
                status=agent_schemas.TaskStatus.RUNNING,
                ports=[agent_schemas.PortMapping(container_port=10999, host_port=10999)],
            )

        async def terminate(self, task_id, timeout_seconds=10, reason=None, message=None):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.TaskInfo(
                id=task_id, status=agent_schemas.TaskStatus.TERMINATED
            )

        async def remove(self, task_id):
            await asyncio.sleep(AGENT_RTT_S)

    class FakeRunner:
        async def healthcheck(self):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.HealthcheckResponse(
                service="tpu-runner", version="bench"
            )

        async def submit(self, body):
            await asyncio.sleep(AGENT_RTT_S)

        async def upload_code(self, blob):
            await asyncio.sleep(AGENT_RTT_S)

        async def run(self):
            await asyncio.sleep(AGENT_RTT_S)

        async def pull(self, since):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.PullResponse(
                job_states=[], job_logs=[], runner_logs=[],
                last_updated=since, has_more=True,
            )

        async def stop(self):
            await asyncio.sleep(AGENT_RTT_S)

    @asynccontextmanager
    async def shim_client_for(jpd, shim_port=None, db=None, project_id=None):
        yield FakeShim()

    @asynccontextmanager
    async def runner_client_for(jpd, runner_port, db=None, project_id=None):
        yield FakeRunner()

    return shim_client_for, runner_client_for


async def bench(n_jobs: int, window_s: float, engine: str) -> dict:
    os.environ.setdefault("DTPU_LOG_LEVEL", "warning")
    if engine in ("postgres", "pgwire"):
        os.environ["DTPU_TEST_DB"] = engine
    else:
        os.environ.pop("DTPU_TEST_DB", None)

    from dstack_tpu.server.background.tasks import (
        process_metrics,
        process_running_jobs,
        process_terminating_jobs,
    )
    from dstack_tpu.server.background.tasks.process_instances import (
        process_instances,
    )
    from dstack_tpu.server.background.tasks.process_runs import process_runs
    from dstack_tpu.server.background.tasks.process_submitted_jobs import (
        process_submitted_jobs,
    )
    from dstack_tpu.server.services import runs as runs_service
    from dstack_tpu.server.services.logs import FileLogStorage, set_log_storage
    from dstack_tpu.server.testing.common import (
        FakeCompute,
        cpu_offer,
        create_test_db,
        create_test_project,
        create_test_user,
        install_fake_backend,
        make_run_spec,
    )

    import tempfile

    set_log_storage(FileLogStorage(Path(tempfile.mkdtemp(prefix="cap-bench-"))))

    shim_for, runner_for = _fake_agents()
    process_running_jobs.shim_client_for = shim_for
    process_running_jobs.runner_client_for = runner_for
    process_terminating_jobs.shim_client_for = shim_for
    process_metrics.runner_client_for = runner_for

    # record every reconciler visit of a RUNNING job (the pull path)
    visits: dict[str, list[float]] = {}
    orig_running = process_running_jobs._process_running

    async def tracked_running(db, job_row, jpd):
        visits.setdefault(job_row["id"], []).append(time.monotonic())
        return await orig_running(db, job_row, jpd)

    process_running_jobs._process_running = tracked_running

    db = await create_test_db()
    _user, user_row = await create_test_user(db)
    project_row = await create_test_project(db, user_row)
    # one offer, unlimited capacity: every job gets its own instance
    compute = FakeCompute(offers=[cpu_offer()])
    install_fake_backend(project_row, compute)

    conf = {"type": "task", "commands": ["python train.py"]}
    t_submit = time.monotonic()
    for i in range(n_jobs):
        await runs_service.submit_run(
            db, project_row, user_row,
            make_run_spec(conf, f"cap-{i:04d}"),
        )

    # drive the loops at their production intervals
    # (server/background/__init__.py)
    loops = [
        (process_runs, 2.0),
        (process_submitted_jobs, 1.0),
        (process_running_jobs.process_running_jobs, 1.0),
        (process_terminating_jobs.process_terminating_jobs, 2.0),
        (process_instances, 2.0),
    ]
    stop = asyncio.Event()

    async def drive(fn, interval):
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                await fn(db)
            except Exception as e:  # pragma: no cover - surfacing only
                print(f"loop {fn.__name__} error: {e}", file=sys.stderr)
            elapsed = time.monotonic() - t0
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(
                    stop.wait(), timeout=max(interval - elapsed, 0.01)
                )

    tasks = [asyncio.create_task(drive(fn, iv)) for fn, iv in loops]

    # --- phase 1: ramp to all-RUNNING ---
    ramp_s = None
    deadline = time.monotonic() + max(300.0, window_s)
    while time.monotonic() < deadline:
        row = await db.fetchone(
            "SELECT COUNT(*) AS n FROM jobs WHERE status = 'running'"
        )
        if row["n"] >= n_jobs:
            ramp_s = time.monotonic() - t_submit
            break
        await asyncio.sleep(0.5)

    # --- phase 2: steady-state visit latency over the window ---
    visits.clear()
    t_window = time.monotonic()
    await asyncio.sleep(window_s)
    stop.set()
    await asyncio.gather(*tasks, return_exceptions=True)

    gaps: list[float] = []
    visited = 0
    for ts in visits.values():
        visited += 1
        # include the edge gaps so a job visited once in the whole
        # window still contributes its true starvation time
        seq = [t_window, *ts, t_window + window_s]
        gaps.extend(b - a for a, b in zip(seq, seq[1:]))
    result = {
        "engine": engine,
        "jobs": n_jobs,
        "ramp_to_all_running_s": round(ramp_s, 1) if ramp_s else None,
        "scheduling_rate_per_min": (
            round(n_jobs / ramp_s * 60, 1) if ramp_s else None
        ),
        "window_s": window_s,
        "jobs_visited_in_window": visited,
        "visit_gap_p50_s": round(statistics.median(gaps), 2) if gaps else None,
        "visit_gap_p95_s": (
            round(statistics.quantiles(gaps, n=20)[18], 2)
            if len(gaps) >= 20 else None
        ),
        "visit_gap_max_s": round(max(gaps), 2) if gaps else None,
        "meets_150_at_2min": bool(
            ramp_s is not None
            and visited >= n_jobs
            and gaps
            and max(gaps) <= 120.0
        ),
    }
    await db.close()
    return result


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--jobs", type=int, default=150)
    p.add_argument("--window", type=float, default=60.0)
    p.add_argument(
        "--db", default="sqlite", choices=["sqlite", "pgwire", "postgres"]
    )
    args = p.parse_args()
    if args.db == "postgres" and not os.environ.get("DTPU_TEST_PG_DSN"):
        print(json.dumps({
            "engine": "postgres",
            "error": "set DTPU_TEST_PG_DSN to a throwaway database; "
            "with asyncpg installed the row measures the asyncpg path, "
            "otherwise the bundled pg_wire client (docs/guides/testing.md)",
        }))
        return 2
    result = asyncio.run(bench(args.jobs, args.window, args.db))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
