"""Control-plane capacity benchmark: the 1500-job envelope.

The reference documents ~150 active jobs with a <= 2 min processing
latency and a 75 jobs/min scheduling ceiling (reference
server/background/__init__.py:45-56). Since the event-driven wakeup
layer (docs/reference/server.md "Reconciliation & wakeups") the number
that matters most is neither of those: it's how fast the control plane
*reacts to a state change* while carrying a big steady-state load.
This tool measures all three:

1. **Scheduling ramp** (``--ramp`` runs, default 150): submit→RUNNING
   through the real pipeline (reconcilers + wakeup drain workers) →
   jobs/min.
2. **Steady-state visit latency**: with ``--jobs`` RUNNING jobs total
   (the non-ramped remainder is bulk-seeded), the safety-net sweeps
   keep pulling every job's agent; p50/p95/max gap between consecutive
   visits of one job. Target: max <= 120 s.
3. **Transition→visit reaction** (``--transitions`` sampled jobs):
   flip a RUNNING job to TERMINATING mid-window and measure how long
   until the terminating reconciler actually visits it. The wakeup
   path makes this independent of the backlog — target p95 < 1 s
   (the acceptance bar; only the safety-net sweep remains pinned to
   the polling interval).

Compute + on-host agents are faked (5 ms simulated RTT per call) so
the measurement isolates the control plane: DB, locking, wakeup queue,
reconciler batching. Engines: sqlite in-memory (default), ``--db
pgwire`` (the bundled wire-protocol fake Postgres), or ``--db
postgres`` with ``DTPU_TEST_PG_DSN``.

The run records its knobs in the output: the 1500-job envelope sizes
the sweep batches to 60 (DTPU_MAX_PROCESSING_*) so a full safety-net
rotation fits in ~25 s; reaction latency comes from the wakeup path
and does not depend on that tuning.

Usage::

    python tools/capacity_bench.py --jobs 1500 --window 60
"""

import argparse
import asyncio
import contextlib
import json
import os
import random
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

AGENT_RTT_S = 0.005  # simulated server<->agent round trip
SWEEP_BATCH = 60  # DTPU_MAX_PROCESSING_* for the 1500-job envelope


def _fake_agents():
    """(shim_client_for, runner_client_for) replacements with canned
    happy-path responses and a small simulated RTT."""
    from contextlib import asynccontextmanager

    from dstack_tpu.agent import schemas as agent_schemas

    class FakeShim:
        async def healthcheck(self):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.HealthcheckResponse(
                service="tpu-shim", version="bench"
            )

        async def submit_task(self, req):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.TaskInfo(
                id=req.id,
                status=agent_schemas.TaskStatus.PULLING,
                ports=[agent_schemas.PortMapping(container_port=10999, host_port=10999)],
            )

        async def get_task(self, task_id):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.TaskInfo(
                id=task_id,
                status=agent_schemas.TaskStatus.RUNNING,
                ports=[agent_schemas.PortMapping(container_port=10999, host_port=10999)],
            )

        async def terminate_task(self, task_id, timeout=10, reason=None, message=None):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.TaskInfo(
                id=task_id, status=agent_schemas.TaskStatus.TERMINATED
            )

        async def remove_task(self, task_id):
            await asyncio.sleep(AGENT_RTT_S)

    class FakeRunner:
        async def healthcheck(self):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.HealthcheckResponse(
                service="tpu-runner", version="bench"
            )

        async def submit(self, body):
            await asyncio.sleep(AGENT_RTT_S)

        async def upload_code(self, blob):
            await asyncio.sleep(AGENT_RTT_S)

        async def run(self):
            await asyncio.sleep(AGENT_RTT_S)

        async def pull(self, since):
            await asyncio.sleep(AGENT_RTT_S)
            return agent_schemas.PullResponse(
                job_states=[], job_logs=[], runner_logs=[],
                last_updated=since, has_more=True,
            )

        async def stop(self):
            await asyncio.sleep(AGENT_RTT_S)

    @asynccontextmanager
    async def shim_client_for(jpd, shim_port=None, db=None, project_id=None):
        yield FakeShim()

    @asynccontextmanager
    async def runner_client_for(jpd, runner_port, db=None, project_id=None):
        yield FakeRunner()

    return shim_client_for, runner_client_for


async def _seed_running_jobs(db, project_row, user_row, n: int) -> None:
    """Bulk-seed n runs × 1 job each directly in RUNNING (+ their BUSY
    instances): the steady-state load the reaction measurement runs
    against, without paying a 1500-run provisioning ramp per engine."""
    if n <= 0:
        return
    from dstack_tpu.core.models.runs import new_uuid, now_utc
    from dstack_tpu.server.db import dumps
    from dstack_tpu.server.services.jobs.configurators import (
        get_job_specs_from_run_spec,
    )
    from dstack_tpu.server.testing.common import cpu_offer, make_run_spec

    conf = {"type": "task", "commands": ["python train.py"]}
    spec_template = make_run_spec(conf, "seed-template")
    job_spec = get_job_specs_from_run_spec(spec_template, 0)[0]
    offer = cpu_offer()
    jpd_template = {
        "backend": "local",
        "instance_type": offer.instance.model_dump(),
        "instance_id": "seeded",
        "hostname": "127.0.0.1",
        "internal_ip": "127.0.0.1",
        "region": offer.region,
        "price": offer.price,
        "username": "bench",
        "ssh_port": 22,
        "dockerized": False,
        "worker_id": 0,
        "hosts": [],
    }
    now = now_utc().isoformat()
    run_rows, inst_rows, job_rows = [], [], []
    for i in range(n):
        name = f"seed-{i:05d}"
        run_id, inst_id, job_id = new_uuid(), new_uuid(), new_uuid()
        spec = spec_template.model_copy(update={"run_name": name})
        run_rows.append((
            run_id, project_row["id"], user_row["id"], name, "running",
            dumps(spec), 1, 0, now, now,
        ))
        inst_rows.append((
            inst_id, project_row["id"], f"inst-{name}", "busy", "local",
            offer.region, dumps({**jpd_template, "instance_id": inst_id}),
            now, now,
        ))
        jspec = job_spec.model_copy(
            update={"job_name": f"{name}-0-0", "run_name": name}
        )
        job_rows.append((
            job_id, run_id, name, project_row["id"], 0, 0, 0,
            f"{name}-0-0", "running", dumps(jspec),
            dumps({**jpd_template, "instance_id": inst_id}),
            dumps({"ports": {"10999": 10999}, "pull_cursor": 0.0}),
            inst_id, 1, now, now,
        ))
    await db.executemany(
        "INSERT INTO runs (id, project_id, user_id, run_name, status, "
        "run_spec, desired_replica_count, deleted, submitted_at, "
        "last_processed_at) VALUES (?,?,?,?,?,?,?,?,?,?)",
        run_rows,
    )
    await db.executemany(
        "INSERT INTO instances (id, project_id, name, status, backend, "
        "region, job_provisioning_data, created_at, last_processed_at) "
        "VALUES (?,?,?,?,?,?,?,?,?)",
        inst_rows,
    )
    await db.executemany(
        "INSERT INTO jobs (id, run_id, run_name, project_id, job_num, "
        "replica_num, submission_num, job_name, status, job_spec, "
        "job_provisioning_data, job_runtime_data, instance_id, "
        "instance_assigned, submitted_at, last_processed_at) "
        "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
        job_rows,
    )


def _quantile(vals, q):
    if not vals:
        return None
    ordered = sorted(vals)
    ix = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return round(ordered[ix], 3)


async def bench(
    n_jobs: int,
    window_s: float,
    engine: str,
    ramp_n: int,
    transitions: int,
) -> dict:
    os.environ.setdefault("DTPU_LOG_LEVEL", "warning")
    # envelope tuning (recorded in the result): sweep batches sized so
    # one full safety-net rotation over n_jobs fits well inside 120 s
    os.environ.setdefault("DTPU_MAX_PROCESSING_JOBS", str(SWEEP_BATCH))
    os.environ.setdefault("DTPU_MAX_PROCESSING_RUNS", str(SWEEP_BATCH))
    os.environ.setdefault("DTPU_MAX_PROCESSING_INSTANCES", str(SWEEP_BATCH))
    if engine in ("postgres", "pgwire"):
        os.environ["DTPU_TEST_DB"] = engine
    else:
        os.environ.pop("DTPU_TEST_DB", None)

    from dstack_tpu.core.models.runs import JobStatus, JobTerminationReason
    from dstack_tpu.server import settings
    from dstack_tpu.server.background.tasks import (
        process_metrics,
        process_running_jobs,
        process_terminating_jobs,
    )
    from dstack_tpu.server.background.tasks.process_instances import (
        process_instances,
    )
    from dstack_tpu.server.background.tasks.process_runs import process_runs
    from dstack_tpu.server.background.tasks.process_submitted_jobs import (
        process_submitted_jobs,
    )
    from dstack_tpu.server.background.wakeup_drain import (
        drain_queue,
        queue_bindings,
    )
    from dstack_tpu.server.services import jobs as jobs_service
    from dstack_tpu.server.services import runs as runs_service
    from dstack_tpu.server.services.logs import FileLogStorage, set_log_storage
    from dstack_tpu.server.testing.common import (
        FakeCompute,
        cpu_offer,
        create_test_db,
        create_test_project,
        create_test_user,
        install_fake_backend,
        make_run_spec,
    )

    import tempfile

    set_log_storage(FileLogStorage(Path(tempfile.mkdtemp(prefix="cap-bench-"))))

    shim_for, runner_for = _fake_agents()
    process_running_jobs.shim_client_for = shim_for
    process_running_jobs.runner_client_for = runner_for
    process_terminating_jobs.shim_client_for = shim_for
    process_metrics.runner_client_for = runner_for

    # record every reconciler visit of a RUNNING job (the pull path)
    visits: dict[str, list[float]] = {}
    orig_running = process_running_jobs._process_running

    async def tracked_running(db, job_row, jpd):
        visits.setdefault(job_row["id"], []).append(time.monotonic())
        return await orig_running(db, job_row, jpd)

    process_running_jobs._process_running = tracked_running

    # record the first terminating-reconciler visit per job (the
    # transition→visit reaction measurement)
    term_visits: dict[str, float] = {}
    orig_term = process_terminating_jobs._process

    async def tracked_term(db, job_id):
        term_visits.setdefault(job_id, time.monotonic())
        return await orig_term(db, job_id)

    process_terminating_jobs._process = tracked_term

    db = await create_test_db()
    _user, user_row = await create_test_user(db)
    project_row = await create_test_project(db, user_row)
    # one offer, unlimited capacity: every job gets its own instance
    compute = FakeCompute(offers=[cpu_offer()])
    install_fake_backend(project_row, compute)

    seeded = max(0, n_jobs - ramp_n)
    t0 = time.monotonic()
    await _seed_running_jobs(db, project_row, user_row, seeded)
    seed_s = time.monotonic() - t0

    conf = {"type": "task", "commands": ["python train.py"]}
    t_submit = time.monotonic()
    for i in range(ramp_n):
        await runs_service.submit_run(
            db, project_row, user_row,
            make_run_spec(conf, f"cap-{i:04d}"),
        )

    # drive the sweeps at their production intervals (the safety net)
    # plus the sharded wakeup drain workers (the event path) — exactly
    # what server/background/__init__.py registers
    loops = [
        (process_runs, 2.0),
        (process_submitted_jobs, 1.0),
        (process_running_jobs.process_running_jobs, 1.0),
        (process_terminating_jobs.process_terminating_jobs, 2.0),
        (process_instances, 2.0),
    ]
    stop = asyncio.Event()

    async def drive(fn, interval):
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                await fn(db)
            except Exception as e:  # pragma: no cover - surfacing only
                print(f"loop error: {e}", file=sys.stderr)
            elapsed = time.monotonic() - t0
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(
                    stop.wait(), timeout=max(interval - elapsed, 0.01)
                )

    tasks = [asyncio.create_task(drive(fn, iv)) for fn, iv in loops]
    nshards = max(1, settings.RECONCILER_SHARDS)
    for queue, handler, namespace in queue_bindings():
        for shard in range(nshards):
            def make(queue=queue, handler=handler, namespace=namespace,
                     shard=shard):
                async def one_drain(db):
                    await drain_queue(
                        db, queue, handler, namespace, shard, nshards
                    )
                return one_drain

            tasks.append(
                asyncio.create_task(
                    drive(make(), settings.WAKEUP_POLL_INTERVAL)
                )
            )

    # --- phase 1: ramp to all-RUNNING ---
    ramp_s = None
    deadline = time.monotonic() + max(300.0, window_s)
    last_print = 0.0
    while time.monotonic() < deadline:
        row = await db.fetchone(
            "SELECT COUNT(*) AS n FROM jobs WHERE status = 'running'"
        )
        if row["n"] >= n_jobs:
            ramp_s = time.monotonic() - t_submit
            break
        if time.monotonic() - last_print > 10:
            last_print = time.monotonic()
            print(
                f"ramp: {row['n']}/{n_jobs} running "
                f"({time.monotonic() - t_submit:.0f}s)",
                file=sys.stderr,
            )
        await asyncio.sleep(0.5)

    # --- phase 2: steady-state window with injected transitions ---
    visits.clear()
    term_visits.clear()
    reactions: list[float] = []
    flips: dict[str, float] = {}
    t_window = time.monotonic()

    async def inject_transitions():
        """Flip sampled RUNNING jobs to TERMINATING spread over the
        window's middle half; reaction = transition commit → first
        terminating-reconciler visit."""
        if transitions <= 0:
            return
        rows = await db.fetchall(
            "SELECT id, run_id FROM jobs WHERE status = 'running' "
            "ORDER BY id LIMIT ?",
            (n_jobs,),
        )
        rng = random.Random(8)
        sample = rng.sample(rows, min(transitions, len(rows)))
        gap = (window_s * 0.5) / max(len(sample), 1)
        await asyncio.sleep(window_s * 0.1)
        for r in sample:
            if stop.is_set():
                break
            flips[r["id"]] = time.monotonic()
            await jobs_service.update_job_status(
                db, r["id"], JobStatus.TERMINATING,
                termination_reason=JobTerminationReason.TERMINATED_BY_USER,
                run_id=r["run_id"],
            )
            await asyncio.sleep(gap)
        # wait (bounded) for every flip to be visited
        flip_deadline = time.monotonic() + 30.0
        while time.monotonic() < flip_deadline:
            if all(j in term_visits for j in flips):
                break
            await asyncio.sleep(0.05)
        for j, t_flip in flips.items():
            if j in term_visits:
                reactions.append(term_visits[j] - t_flip)

    injector = asyncio.create_task(inject_transitions())
    await asyncio.sleep(window_s)
    await injector
    stop.set()
    await asyncio.gather(*tasks, return_exceptions=True)

    gaps: list[float] = []
    visited = 0
    for jid, ts in visits.items():
        if jid in flips:
            # deliberately terminated mid-window: its visit stream ends
            # by design, so its trailing edge gap is not starvation
            continue
        visited += 1
        # include the edge gaps so a job visited once in the whole
        # window still contributes its true starvation time
        seq = [t_window, *ts, t_window + window_s]
        gaps.extend(b - a for a, b in zip(seq, seq[1:]))
    from dstack_tpu.server.services.wakeups import get_reconcile_registry

    reg = get_reconcile_registry()
    result = {
        "engine": engine,
        "jobs": n_jobs,
        "ramp_jobs": ramp_n,
        "seeded_jobs": seeded,
        "seed_s": round(seed_s, 1),
        "sweep_batch": SWEEP_BATCH,
        "reconciler_shards": nshards,
        "wakeup_poll_interval_s": settings.WAKEUP_POLL_INTERVAL,
        "ramp_to_all_running_s": round(ramp_s, 1) if ramp_s else None,
        "scheduling_rate_per_min": (
            round(ramp_n / ramp_s * 60, 1) if ramp_s else None
        ),
        "window_s": window_s,
        "jobs_visited_in_window": visited,
        "visit_gap_p50_s": round(statistics.median(gaps), 2) if gaps else None,
        "visit_gap_p95_s": (
            round(statistics.quantiles(gaps, n=20)[18], 2)
            if len(gaps) >= 20 else None
        ),
        "visit_gap_max_s": round(max(gaps), 2) if gaps else None,
        "transitions_injected": transitions,
        "transitions_visited": len(reactions),
        "reaction_p50_s": _quantile(reactions, 0.50),
        "reaction_p95_s": _quantile(reactions, 0.95),
        "reaction_max_s": _quantile(reactions, 1.0),
        "wakeups_delivered": int(
            reg.family("dtpu_reconcile_wakeups_delivered_total").value(
                "terminating_jobs"
            )
        ),
        "meets_envelope": bool(
            ramp_s is not None
            and visited >= n_jobs - transitions
            and gaps
            and max(gaps) <= 120.0
            and len(reactions) >= min(transitions, 1)
            and (_quantile(reactions, 0.95) or 99.0) < 1.0
        ),
    }
    await db.close()
    return result


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--jobs", type=int, default=1500)
    p.add_argument("--window", type=float, default=60.0)
    p.add_argument(
        "--ramp", type=int, default=150,
        help="jobs submitted through the real pipeline (the rest of "
        "--jobs is bulk-seeded RUNNING)",
    )
    p.add_argument(
        "--transitions", type=int, default=100,
        help="RUNNING jobs flipped to TERMINATING mid-window for the "
        "reaction-latency measurement",
    )
    p.add_argument(
        "--db", default="sqlite", choices=["sqlite", "pgwire", "postgres"]
    )
    args = p.parse_args()
    if args.db == "postgres" and not os.environ.get("DTPU_TEST_PG_DSN"):
        print(json.dumps({
            "engine": "postgres",
            "error": "set DTPU_TEST_PG_DSN to a throwaway database; "
            "with asyncpg installed the row measures the asyncpg path, "
            "otherwise the bundled pg_wire client (docs/guides/testing.md)",
        }))
        return 2
    result = asyncio.run(
        bench(
            args.jobs, args.window, args.db,
            ramp_n=min(args.ramp, args.jobs),
            transitions=args.transitions,
        )
    )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
