"""A/B the serving decode attention paths on the real chip: masked
einsum (reads the full ``Tmax`` cache row per slot per step) vs the
ragged pallas kernel (``ops/flash_decode`` — each slot reads only the
blocks covering its own length).

One JSON line per (kernel, config) cell, via the serve bench's own
measurement loop so the numbers are directly comparable with the other
serving evidence. The configs bracket the regimes the kernel targets:
the headline serve shape (short context fully written — parity check:
ragged ≈ full there), and a long-max_seq short-prompt shape where most
of every cache row is unwritten (ragged should win on HBM traffic).
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _tpu_reachable(timeout: float = 90.0) -> bool:
    from dstack_tpu.utils.tpu_probe import tpu_reachable  # one impl

    return tpu_reachable(timeout=timeout)


def main() -> int:
    smoke = "--cpu-smoke" in sys.argv
    if smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif not _tpu_reachable():
        print(json.dumps({
            "error": "TPU unreachable (tunnel down); pass --cpu-smoke "
                     "for an interpret-mode smoke run"
        }))
        return 1

    from dstack_tpu.serve.bench import run_bench
    # the head_dim-64 tiny is the smallest kernel-eligible preset
    model = "llama-tiny-64" if smoke else "llama-3.2-1b"
    cells = (
        # (batch, max_seq, prompt_len, gen_len, turbo)
        [(2, 256, 32, 8, 4)] if smoke else [
            (16, 1024, 256, 64, 128),  # headline serve shape
            (8, 2048, 256, 64, 128),  # long rows, short prompts: ragged regime
        ]
    )
    for batch, max_seq, plen, glen, turbo in cells:
        for kernel in ("einsum", "flash"):
            try:
                r = run_bench(
                    model=model, batch=batch, max_seq=max_seq,
                    prompt_len=plen, gen_len=glen, spec_draft=0,
                    turbo_steps=turbo, kv_quant="int8",
                    decode_kernel=kernel,
                )
            except ValueError as e:  # unsupported shape → record, move on
                print(json.dumps({"decode_kernel": kernel, "error": str(e)}))
                continue
            r["extra"]["max_seq"] = max_seq
            r["extra"]["prompt_len"] = plen
            print(json.dumps(r), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
