"""TPU tunnel window-watcher (VERDICT r4 item 1).

The axon tunnel to the real chip comes and goes; rounds 2-4 all missed
their window. This watcher makes capture automatic: probe
``jax.devices()`` in a short-lived subprocess every PROBE_INTERVAL
seconds, and the moment the tunnel answers, fire
``tools/tpu_capture.py`` for every phase that does not yet have a
successful entry in the evidence file. Keeps watching until every phase
is captured or has burned MAX_ATTEMPTS failed tries (a tunnel drop
mid-window leaves the remaining phases for the next window; a
deterministically failing phase is abandoned instead of retried
forever).

Run it once in the background for the whole session (pidfile-managed —
never pkill by name, the invoking shell's own command line matches):

    tools/watcher_ctl.sh start
"""

import json
import subprocess
import sys
import time
from datetime import datetime, timezone
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from tpu_capture import EVIDENCE, PHASES  # single source of truth

REPO = Path(__file__).resolve().parents[1]
PROBE_INTERVAL = 180  # seconds between probes while the tunnel is down
PROBE_TIMEOUT = 90  # jax TPU init hangs (not errors) when the tunnel is down
MAX_ATTEMPTS = 3  # real phase failures before giving up on it
MAX_TIMEOUTS = 6  # timeout-looking failures get a higher cap (a tunnel
# drop mid-capture also times out, so one timeout is weak evidence of a
# broken phase — but a phase that hangs 6 times with the tunnel up is)

PROBE_SNIPPET = (
    "import jax; d = jax.devices(); "
    "assert d and d[0].platform == 'tpu', d; print('tpu ok', len(d))"
)


def _log(msg: str) -> None:
    ts = datetime.now(timezone.utc).strftime("%H:%M:%SZ")
    print(f"[{ts}] {msg}", flush=True)


def drop_class(error) -> bool:
    """Errors that look like a tunnel drop rather than a broken phase:
    timeouts (capture killed the hung tool), CPU fallbacks (the tool
    lost the chip mid-window and smoke-completed on CPU), JAX backend
    init failures (UNAVAILABLE — all three appear in this round's own
    evidence file), and the tools' "TPU unreachable" self-reports.
    These count against the lenient MAX_TIMEOUTS cap, not MAX_ATTEMPTS
    — a flappy tunnel must not permanently abandon a healthy phase."""
    err = str(error)
    if err.startswith(("timeout", "cpu fallback")):
        return True
    return any(sig in err for sig in (
        "UNAVAILABLE",
        "Unable to initialize backend",
        "TPU unreachable",
    ))


def probe() -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_SNIPPET],
            cwd=REPO, timeout=PROBE_TIMEOUT, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


def captured_ok() -> set:
    """Phases with at least one successful (non-error) evidence entry."""
    ok = set()
    if not EVIDENCE.exists():
        return ok
    try:
        runs = json.loads(EVIDENCE.read_text()).get("runs", [])
    except ValueError:
        return ok
    return {r["phase"] for r in runs if "error" not in r}


def main() -> int:
    _log(f"watcher up; probing every {PROBE_INTERVAL}s; phases: {PHASES}")
    # attempts are counted IN-SESSION only: a capture try that ends with
    # the TUNNEL DOWN (probe fails right after) was a drop, not a phase
    # failure, and doesn't count toward giving up — past sessions' error
    # entries in the evidence file never count
    attempts: dict = {}
    timeouts: dict = {}
    while True:
        ok = captured_ok()
        missing = [p for p in PHASES if p not in ok]
        live = [
            p for p in missing
            if attempts.get(p, 0) < MAX_ATTEMPTS
            and timeouts.get(p, 0) < MAX_TIMEOUTS
        ]
        if not missing:
            _log("all phases captured — watcher done")
            return 0
        if not live:
            _log(f"gave up: {missing} exhausted their attempts — watcher done")
            return 1
        if probe():
            nums = ",".join(str(PHASES.index(p) + 1) for p in live)
            _log(f"TUNNEL UP — capturing phases {nums} ({live})")
            subprocess.run(
                [sys.executable, "tools/tpu_capture.py", "--phases", nums],
                cwd=REPO,
            )
            still_missing = [p for p in live if p not in captured_ok()]
            if still_missing and probe():
                # tunnel is up NOW — but a drop-and-recover mid-capture
                # looks the same, and those phases would be timeouts or
                # CPU fallbacks: only count failures whose last evidence
                # entry is a real error (nonzero exit with output) —
                # never timeouts, never drop-class cpu-fallback marks
                timed_out = set()
                try:
                    runs = json.loads(EVIDENCE.read_text()).get("runs", [])
                    for r in runs:
                        if "error" in r:
                            is_to = drop_class(r["error"])
                            (timed_out.add if is_to else timed_out.discard)(
                                r["phase"]
                            )
                except (ValueError, OSError):
                    pass
                failed = [p for p in still_missing if p not in timed_out]
                for p in failed:
                    attempts[p] = attempts.get(p, 0) + 1
                for p in still_missing:
                    if p in timed_out:
                        timeouts[p] = timeouts.get(p, 0) + 1
                if still_missing:
                    _log(
                        f"capture incomplete (tunnel up): failed={failed} "
                        f"timed_out={[p for p in still_missing if p in timed_out]}"
                    )
            # never spin: a capture that failed instantly would
            # otherwise loop back-to-back
            time.sleep(30)
            continue
        _log(f"tunnel down (missing: {len(missing)} phases)")
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    sys.exit(main())
