"""TPU tunnel window-watcher (VERDICT r4 item 1).

The axon tunnel to the real chip comes and goes; rounds 2-4 all missed
their window. This watcher makes capture automatic: probe
``jax.devices()`` in a short-lived subprocess every PROBE_INTERVAL
seconds, and the moment the tunnel answers, fire
``tools/tpu_capture.py`` for every phase that does not yet have a
successful entry in ``BENCH_TPU_r05_evidence.json``. Keeps watching
until all phases are captured (a tunnel drop mid-window leaves the
remaining phases for the next window).

Run it once in the background for the whole session:

    nohup python tools/tpu_watcher.py >> tools/tpu_watcher.log 2>&1 &
"""

import json
import subprocess
import sys
import time
from datetime import datetime, timezone
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
EVIDENCE = REPO / "BENCH_TPU_r05_evidence.json"
PROBE_INTERVAL = 180  # seconds between probes while the tunnel is down
PROBE_TIMEOUT = 90  # jax TPU init hangs (not errors) when the tunnel is down
ALL_PHASES = ("headline_bench", "serve_8b_int8", "latency_under_load", "mfu_sweep")
PHASE_NUM = {name: i + 1 for i, name in enumerate(ALL_PHASES)}

PROBE_SNIPPET = (
    "import jax; d = jax.devices(); "
    "assert d and d[0].platform == 'tpu', d; print('tpu ok', len(d))"
)


def _log(msg: str) -> None:
    ts = datetime.now(timezone.utc).strftime("%H:%M:%SZ")
    print(f"[{ts}] {msg}", flush=True)


def probe() -> bool:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_SNIPPET],
            cwd=REPO, timeout=PROBE_TIMEOUT, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


def captured_phases() -> set:
    """Phase names with at least one successful (non-error) entry."""
    if not EVIDENCE.exists():
        return set()
    try:
        runs = json.loads(EVIDENCE.read_text()).get("runs", [])
    except ValueError:
        return set()
    return {r["phase"] for r in runs if "error" not in r}


def main() -> int:
    _log(f"watcher up; probing every {PROBE_INTERVAL}s")
    while True:
        missing = [p for p in ALL_PHASES if p not in captured_phases()]
        if not missing:
            _log("all phases captured — watcher done")
            return 0
        if probe():
            nums = ",".join(str(PHASE_NUM[p]) for p in missing)
            _log(f"TUNNEL UP — capturing phases {nums} ({missing})")
            subprocess.run(
                [sys.executable, "tools/tpu_capture.py", "--phases", nums],
                cwd=REPO,
            )
            continue  # immediately re-check what is still missing
        _log(f"tunnel down (missing: {len(missing)} phases)")
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    sys.exit(main())
