#!/bin/bash
# Manage the TPU window watcher via a pidfile (pkill -f is unsafe here:
# the invoking shell's own command line contains the script name).
set -e
SELF="$(cd "$(dirname "$0")" && pwd)/$(basename "$0")"
cd "$(dirname "$SELF")/.."
PIDFILE=tools/tpu_watcher.pid
case "${1:-status}" in
  start)
    if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
      echo "already running: $(cat "$PIDFILE")"
      exit 0
    fi
    setsid nohup python tools/tpu_watcher.py >> tools/tpu_watcher.log 2>&1 < /dev/null &
    echo $! > "$PIDFILE"
    echo "started: $(cat "$PIDFILE")"
    ;;
  stop)
    if [ -f "$PIDFILE" ]; then
      # the watcher runs in its own setsid session; kill the whole
      # group so an in-flight tpu_capture.py child goes with it
      kill -- -"$(cat "$PIDFILE")" 2>/dev/null \
        || kill "$(cat "$PIDFILE")" 2>/dev/null || true
    fi
    rm -f "$PIDFILE"
    echo stopped
    ;;
  restart)
    "$SELF" stop; sleep 1; "$SELF" start
    ;;
  status)
    if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
      echo "running: $(cat "$PIDFILE")"
    else
      echo "not running"
    fi
    ;;
  *)
    echo "usage: $0 {start|stop|restart|status}" >&2
    exit 1
    ;;
esac
