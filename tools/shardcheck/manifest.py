"""The registered manifest of the engine's jit surface + parallel/ entries.

Each :class:`Entry` names one jitted function the serve engine
dispatches (the ``_watch``/``_watch_jit`` names in
``serve/engine.py``) or one ``parallel/`` entry point, and knows how
to build abstract arguments for it and what output structure the
engine relies on. The runner (``__main__``) eval_shapes every entry
over every :data:`GRIDS` mesh; :func:`engine_jit_sites` is the
AST-level coverage scan that forces new engine jit sites to register
here.

This module imports JAX lazily — ``--validate`` (manifest
well-formedness + coverage) runs with no JAX at all.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

REPO = Path(__file__).resolve().parent.parent.parent
ENGINE_PATH = REPO / "dstack_tpu" / "serve" / "engine.py"

#: AbstractMesh grids the gate verifies against — axis names must be
#: drawn from parallel/mesh.py AXES (dtpu-lint DTPU012 checks that
#: statically; here a typo fails the abstract trace).
GRIDS: dict[str, tuple[tuple[str, int], ...]] = {
    "tp2": (("tp", 2),),
    "tp4": (("tp", 4),),
    "dp2xtp2": (("dp", 2), ("tp", 2)),
}

# abstract problem dims — chosen so every grid divides evenly and the
# flash-decode cache-length floor (multiples of 128) is respected
B = 2        # engine batch / slots
T = 128      # max_seq (cache length)
S = 4        # speculative verify width
C = 16       # prefill chunk length
G = 2        # packed prefill group
STEPS = 4    # turbo decode_loop steps
SEQ = 64     # parallel/ attention sequence length
HEADS = 8    # divisible by tp4 and by sp=2 (ulysses head split)
KV_HEADS = 4
HEAD_DIM = 32


@dataclass(frozen=True)
class Entry:
    """One verified jit surface: ``build(ctx)`` returns
    ``(fn, args, kwargs)`` of abstract values; ``check(ctx, out)``
    raises AssertionError when the traced output breaks the engine's
    structural contract (shapes/dtypes/donation aliasing)."""

    name: str
    kind: str  # "engine" | "parallel"
    build: Callable
    check: Callable
    #: getattr path on the jax module that must exist for this entry
    #: to trace under the installed jax (None = always runnable)
    requires: Optional[str] = None
    notes: str = ""


MANIFEST: dict[str, Entry] = {}


def register(name: str, kind: str, *, requires: str = None, notes: str = ""):
    def deco(build_and_check):
        build, check = build_and_check()
        if name in MANIFEST:
            raise ValueError(f"duplicate shardcheck entry {name!r}")
        MANIFEST[name] = Entry(name, kind, build, check, requires, notes)
        return build_and_check

    return deco


# ---------------------------------------------------------------------------
# abstract context: config + mesh + eval_shape'd params/cache per grid
# ---------------------------------------------------------------------------


@dataclass
class Ctx:
    grid: str
    mesh: object  # jax.sharding.AbstractMesh
    config: object  # LlamaConfig
    params: object  # abstract param tree
    cache: dict  # abstract KV cache tree
    _sds: Callable = field(default=None, repr=False)

    def sds(self, shape, dtype):
        return self._sds(shape, dtype)

    def i32(self, *shape):
        import jax.numpy as jnp

        return self.sds(shape, jnp.int32)

    def f32(self, *shape):
        import jax.numpy as jnp

        return self.sds(shape, jnp.float32)


def make_ctx(grid: str) -> Ctx:
    """Abstract config/params/cache for one mesh grid — device-free:
    params and cache come out of ``jax.eval_shape`` (the cache builder
    jits with ``out_shardings`` over the AbstractMesh, which traces
    fine without devices)."""
    from dataclasses import replace
    from functools import partial

    import jax
    from jax.sharding import AbstractMesh

    from dstack_tpu.models import llama
    from dstack_tpu.serve import engine as eng

    # LLAMA_TINY widened so heads/kv-heads/mlp divide every grid's tp
    config = replace(
        llama.LLAMA_TINY,
        n_heads=HEADS,
        n_kv_heads=KV_HEADS,
        hidden_size=HEADS * HEAD_DIM,
        intermediate_size=2 * HEADS * HEAD_DIM,
        max_seq_len=2 * T,
    )
    mesh = AbstractMesh(GRIDS[grid])
    params = jax.eval_shape(partial(llama.init_params, config), jax.random.key(0))
    cache = jax.eval_shape(lambda: eng.init_cache(config, B, T, mesh=mesh))
    return Ctx(grid, mesh, config, params, cache, _sds=jax.ShapeDtypeStruct)


# ---------------------------------------------------------------------------
# structural checks
# ---------------------------------------------------------------------------


def _assert_shape(out, shape, dtype=None, what="output"):
    assert tuple(out.shape) == tuple(shape), (
        f"{what}: shape {tuple(out.shape)} != expected {tuple(shape)}"
    )
    if dtype is not None:
        assert out.dtype == dtype, (
            f"{what}: dtype {out.dtype} != expected {dtype}"
        )


def _assert_cache_roundtrip(ctx, cache_out, what):
    """Donated-cache contract: the returned cache tree must be
    structurally identical to the input (donation aliasing requires
    it; a drift here is a silent reallocation per step on device)."""
    import jax

    in_s = jax.tree.map(lambda a: (tuple(a.shape), str(a.dtype)), ctx.cache)
    out_s = jax.tree.map(lambda a: (tuple(a.shape), str(a.dtype)), cache_out)
    assert in_s == out_s, (
        f"{what}: cache tree drifted across the step: {in_s} -> {out_s}"
    )


# ---------------------------------------------------------------------------
# engine jit surface (names match _watch/_watch_jit registration)
# ---------------------------------------------------------------------------


@register("decode", "engine")
def _decode():
    def build(ctx):
        from functools import partial

        from dstack_tpu.serve import engine as eng

        fn = partial(
            eng.decode_step, config=ctx.config, decode_kernel="einsum",
            mesh=ctx.mesh,
        )
        return fn, (ctx.params, ctx.cache, ctx.i32(B), ctx.i32(B)), {}

    def check(ctx, out):
        import jax.numpy as jnp

        logits, cache = out
        _assert_shape(logits, (B, ctx.config.vocab_size), jnp.float32, "logits")
        _assert_cache_roundtrip(ctx, cache, "decode")

    return build, check


@register("verify", "engine")
def _verify():
    def build(ctx):
        from functools import partial

        import jax.numpy as jnp

        from dstack_tpu.serve import engine as eng

        fn = partial(
            eng.verify_step, config=ctx.config, decode_kernel="einsum",
            mesh=ctx.mesh,
        )
        args = (ctx.params, ctx.cache, ctx.i32(B, S), ctx.i32(B))
        return fn, args, {"write_mask": ctx.sds((B,), jnp.bool_)}

    def check(ctx, out):
        import jax.numpy as jnp

        logits, cache = out
        _assert_shape(
            logits, (B, S, ctx.config.vocab_size), jnp.float32, "logits"
        )
        _assert_cache_roundtrip(ctx, cache, "verify")

    return build, check


@register("sample", "engine")
def _sample():
    def build(ctx):
        import jax.numpy as jnp

        from dstack_tpu.serve import engine as eng

        v = ctx.config.vocab_size
        args = (
            ctx.f32(B, v),                       # logits
            ctx.sds((B, 2), jnp.uint32),         # key_data
            ctx.f32(B), ctx.f32(B), ctx.i32(B),  # temperature, top_p, top_k
            ctx.f32(B),                          # rep_pen
            ctx.i32(B, v),                       # counts
            ctx.f32(B), ctx.f32(B),              # pres_pen, freq_pen
            ctx.i32(B, v),                       # gen_counts
        )
        return eng.sample, args, {}

    def check(ctx, out):
        import jax.numpy as jnp

        tokens, key_data = out
        _assert_shape(tokens, (B,), jnp.int32, "tokens")
        _assert_shape(key_data, (B, 2), jnp.uint32, "key_data")

    return build, check


@register("argmax", "engine")
def _argmax():
    def build(ctx):
        from functools import partial

        import jax.numpy as jnp

        return (
            partial(jnp.argmax, axis=-1),
            (ctx.f32(B, ctx.config.vocab_size),),
            {},
        )

    def check(ctx, out):
        _assert_shape(out, (B,), None, "argmax")

    return build, check


@register("advance_state", "engine")
def _advance_state():
    def build(ctx):
        from functools import partial

        import jax.numpy as jnp

        from dstack_tpu.serve import engine as eng

        fn = partial(eng.advance_decode_state, max_seq=T)
        args = (
            ctx.i32(B), ctx.i32(B), ctx.i32(B),
            ctx.sds((B,), jnp.bool_), ctx.i32(B), ctx.i32(B),
        )
        return fn, args, {}

    def check(ctx, out):
        import jax.numpy as jnp

        tok, pos, rem, act = out
        for a, name in ((tok, "tok"), (pos, "pos"), (rem, "rem")):
            _assert_shape(a, (B,), jnp.int32, name)
        _assert_shape(act, (B,), jnp.bool_, "act")

    return build, check


@register("logprobs", "engine")
def _logprobs():
    def build(ctx):
        from dstack_tpu.serve import engine as eng

        return (
            eng.token_logprobs,
            (ctx.f32(B, ctx.config.vocab_size), ctx.i32(B)),
            {},
        )

    def check(ctx, out):
        from dstack_tpu.serve.engine import TOP_LOGPROBS

        chosen, top_ids, top_lp = out
        _assert_shape(chosen, (B,), None, "chosen")
        _assert_shape(top_ids, (B, TOP_LOGPROBS), None, "top_ids")
        _assert_shape(top_lp, (B, TOP_LOGPROBS), None, "top_lp")

    return build, check


@register("mark_seen", "engine")
def _mark_seen():
    def build(ctx):
        from dstack_tpu.serve import engine as eng

        v = ctx.config.vocab_size
        return (
            eng._mark_seen,
            (ctx.i32(B, v), ctx.i32(B, v), ctx.i32(B), ctx.i32(B)),
            {},
        )

    def check(ctx, out):
        v = ctx.config.vocab_size
        _assert_shape(out[0], (B, v), None, "counts")
        _assert_shape(out[1], (B, v), None, "gen_counts")

    return build, check


@register("mark_prompt", "engine")
def _mark_prompt():
    def build(ctx):
        from dstack_tpu.serve import engine as eng

        v = ctx.config.vocab_size
        args = (
            ctx.i32(B, v), ctx.i32(B, v), ctx.i32(), ctx.i32(T), ctx.i32()
        )
        return eng._mark_prompt, args, {}

    def check(ctx, out):
        v = ctx.config.vocab_size
        _assert_shape(out[0], (B, v), None, "counts")
        _assert_shape(out[1], (B, v), None, "gen_counts")

    return build, check


@register("skip_key", "engine")
def _skip_key():
    def build(ctx):
        import jax.numpy as jnp

        from dstack_tpu.serve import engine as eng

        return eng.skip_key_data, (ctx.sds((2,), jnp.uint32), ctx.i32()), {}

    def check(ctx, out):
        import jax.numpy as jnp

        _assert_shape(out, (2,), jnp.uint32, "key_data")

    return build, check


@register("chunk", "engine")
def _chunk():
    def build(ctx):
        from functools import partial

        from dstack_tpu.serve import engine as eng

        fn = partial(eng.prefill_chunk_step, config=ctx.config, start=0)
        return fn, (ctx.params, ctx.cache, ctx.i32(1, C), ctx.i32(), ctx.i32()), {}

    def check(ctx, out):
        logits, cache = out
        _assert_shape(logits, (1, ctx.config.vocab_size), None, "logits")
        _assert_cache_roundtrip(ctx, cache, "chunk")

    return build, check


@register("packed", "engine")
def _packed():
    def build(ctx):
        from functools import partial

        from dstack_tpu.serve import engine as eng

        fn = partial(eng.prefill_packed_step, config=ctx.config)
        args = (
            ctx.params, ctx.cache, ctx.i32(G, C), ctx.i32(G), ctx.i32(G),
            ctx.i32(G),
        )
        return fn, args, {}

    def check(ctx, out):
        logits, cache = out
        _assert_shape(logits, (G, ctx.config.vocab_size), None, "logits")
        _assert_cache_roundtrip(ctx, cache, "packed")

    return build, check


@register("copy", "engine")
def _copy():
    def build(ctx):
        from functools import partial

        from dstack_tpu.serve import engine as eng

        fn = partial(eng.copy_cache_prefix, p=C)
        return fn, (ctx.cache, ctx.i32(), ctx.i32()), {}

    def check(ctx, out):
        _assert_cache_roundtrip(ctx, out, "copy")

    return build, check


@register("turbo", "engine")
def _turbo():
    def build(ctx):
        from functools import partial

        import jax.numpy as jnp

        from dstack_tpu.serve import engine as eng

        fn = partial(
            eng.decode_loop, config=ctx.config, steps=STEPS, max_seq=T,
            decode_kernel="einsum", mesh=ctx.mesh,
        )
        args = (
            ctx.params, ctx.cache, ctx.i32(B), ctx.i32(B), ctx.i32(B),
            ctx.sds((B,), jnp.bool_), ctx.i32(B),
        )
        return fn, args, {}

    def check(ctx, out):
        toks, cache = out[0], out[1]
        _assert_shape(toks, (STEPS, B), None, "tokens")
        _assert_cache_roundtrip(ctx, cache, "turbo")

    return build, check


# ---------------------------------------------------------------------------
# parallel/ entry points — run over the grid's "tp" axis (every grid
# has one); the trace validates axis binding + divisibility end to end
# ---------------------------------------------------------------------------


def _qkv(ctx):
    return (
        ctx.f32(B, HEADS, SEQ, HEAD_DIM),
        ctx.f32(B, KV_HEADS, SEQ, HEAD_DIM),
        ctx.f32(B, KV_HEADS, SEQ, HEAD_DIM),
    )


@register("ring_attention", "parallel", notes="xla ring over the tp axis")
def _ring():
    def build(ctx):
        from functools import partial

        from dstack_tpu.parallel.ring_attention import ring_attention

        fn = partial(ring_attention, mesh=ctx.mesh, axis_name="tp", impl="xla")
        return fn, _qkv(ctx), {}

    def check(ctx, out):
        _assert_shape(out, (B, HEADS, SEQ, HEAD_DIM), None, "ring out")

    return build, check


@register("ulysses_attention", "parallel", notes="head<->seq all_to_all over tp")
def _ulysses():
    def build(ctx):
        from functools import partial

        from dstack_tpu.parallel.ulysses import ulysses_attention

        fn = partial(ulysses_attention, mesh=ctx.mesh, axis_name="tp")
        return fn, _qkv(ctx), {}

    def check(ctx, out):
        _assert_shape(out, (B, HEADS, SEQ, HEAD_DIM), None, "ulysses out")

    return build, check


@register(
    "pipeline_apply", "parallel", requires="shard_map",
    notes="GPipe loop over tp as the stage axis; needs jax.shard_map "
    "(partial-manual axis_names), absent from older jax — skipped there",
)
def _pipeline():
    def build(ctx):
        from functools import partial

        import jax.numpy as jnp

        from dstack_tpu.parallel.pipeline import pipeline_apply

        pp = dict(GRIDS[ctx.grid])["tp"]
        d = 16

        def stage_fn(local, x, extras):
            return x @ local["w"][0], jnp.float32(0.0)

        fn = partial(
            pipeline_apply, stage_fn, mesh=ctx.mesh, axis_name="tp",
            extras=None,
        )
        args = (
            {"w": ctx.f32(pp, 1, d, d)},  # [pp, L/pp, d, d]
            ctx.f32(4, 8, d),             # [n_micro, mb, d]
        )
        return fn, args, {}

    def check(ctx, out):
        ys, aux = out
        _assert_shape(ys, (4, 8, 16), None, "pipeline out")
        _assert_shape(aux, (), None, "aux")

    return build, check


# ---------------------------------------------------------------------------
# coverage: every named engine jit site must have a manifest entry
# ---------------------------------------------------------------------------


def engine_jit_sites(path: Path = ENGINE_PATH) -> list[tuple[str, int]]:
    """(name, line) for every ``_watch(jax.jit(...), "name")`` and
    ``self._watch_jit(jax.jit(...), "name", ...)`` registration in the
    engine — pure AST, no imports, so ``--validate`` stays offline."""
    tree = ast.parse(path.read_text(), filename=str(path))
    sites = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (
            fn.id
            if isinstance(fn, ast.Name)
            else fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if name not in ("_watch", "_watch_jit"):
            continue
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            sites.append((node.args[1].value, node.lineno))
    return sites


def coverage_failures(
    path: Path = ENGINE_PATH, manifest: dict = None
) -> list[str]:
    """Engine jit names with no manifest entry (the gate's teeth: a
    new jit site must register here before it ships)."""
    manifest = MANIFEST if manifest is None else manifest
    engine_names = {n for n, e in manifest.items() if e.kind == "engine"}
    out = []
    for name, line in engine_jit_sites(path):
        if name not in manifest:
            out.append(
                f"engine jit site '{name}' ({path.name}:{line}) has no "
                "tools/shardcheck manifest entry — register it in "
                "tools/shardcheck/manifest.py so the abstract-trace gate "
                "covers it"
            )
    seen = {n for n, _ in engine_jit_sites(path)}
    for name in sorted(engine_names - seen):
        out.append(
            f"manifest entry '{name}' (kind=engine) matches no "
            f"_watch/_watch_jit site in {path.name} — stale entry, remove "
            "or rename it"
        )
    return out


def validate_manifest(manifest: dict = None) -> list[str]:
    """Offline structural validation (no JAX): entries well-formed,
    grids declared, names unique by construction."""
    manifest = MANIFEST if manifest is None else manifest
    problems = []
    if not GRIDS:
        problems.append("no mesh grids declared")
    for gname, axes in GRIDS.items():
        for ax, n in axes:
            if not (isinstance(ax, str) and isinstance(n, int) and n >= 2):
                problems.append(f"grid {gname}: bad axis spec ({ax!r}, {n!r})")
    for name, e in manifest.items():
        if e.kind not in ("engine", "parallel"):
            problems.append(f"entry {name}: unknown kind {e.kind!r}")
        if not callable(e.build) or not callable(e.check):
            problems.append(f"entry {name}: build/check not callable")
    return problems
