"""CLI: ``python -m tools.shardcheck [--validate] [--grid G] [--entry E]``.

Exit 0 when every manifest entry abstract-traces cleanly over every
AbstractMesh grid AND every named engine jit site is registered;
exit 1 on any trace failure, contract-check failure, or coverage gap.
``--validate`` runs only the offline checks (no JAX import) — the
fast pre-commit half of the gate.
"""

import argparse
import os
import sys
import traceback
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

_REPO = Path(__file__).resolve().parent.parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.shardcheck.manifest import (  # noqa: E402
    GRIDS,
    MANIFEST,
    Entry,
    coverage_failures,
    make_ctx,
    validate_manifest,
)


@dataclass
class Result:
    entry: str
    grid: str
    status: str  # "pass" | "fail" | "skip"
    detail: str = ""


def _missing_requirement(entry: Entry) -> Optional[str]:
    if entry.requires is None:
        return None
    import jax

    if getattr(jax, entry.requires, None) is None:
        return f"jax.{entry.requires} unavailable in jax {jax.__version__}"
    return None


def run_entry(entry: Entry, grid: str, ctx=None) -> Result:
    """Abstract-trace one entry over one grid (device-free)."""
    import jax

    missing = _missing_requirement(entry)
    if missing:
        return Result(entry.name, grid, "skip", missing)
    ctx = make_ctx(grid) if ctx is None else ctx
    try:
        fn, args, kwargs = entry.build(ctx)
        out = jax.eval_shape(fn, *args, **kwargs)
        entry.check(ctx, out)
    except Exception as e:  # trace/shape/axis failures are the product
        tb = traceback.format_exc(limit=3)
        return Result(
            entry.name, grid, "fail", f"{type(e).__name__}: {e}\n{tb}"
        )
    return Result(entry.name, grid, "pass")


def run_all(
    grids=None, entries=None, verbose: bool = False
) -> list[Result]:
    results = []
    for grid in grids or GRIDS:
        ctx = make_ctx(grid)
        for name in entries or MANIFEST:
            r = run_entry(MANIFEST[name], grid, ctx)
            results.append(r)
            if verbose or r.status != "pass":
                line = f"[{r.status.upper():4}] {grid:8} {name}"
                if r.detail:
                    line += f" — {r.detail.splitlines()[0]}"
                print(line, file=sys.stderr)
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shardcheck",
        description="device-free SPMD verification of the serve jit "
        "surface over AbstractMesh grids (docs/reference/lint.md)",
    )
    ap.add_argument(
        "--validate",
        action="store_true",
        help="offline checks only: manifest well-formedness + engine "
        "jit-site coverage (no JAX import, no tracing)",
    )
    ap.add_argument(
        "--grid", choices=sorted(GRIDS), action="append",
        help="run only this mesh grid (repeatable; default: all)",
    )
    ap.add_argument(
        "--entry", choices=sorted(MANIFEST), action="append",
        help="run only this manifest entry (repeatable; default: all)",
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="print every entry's status, not just failures",
    )
    args = ap.parse_args(argv)

    problems = validate_manifest() + coverage_failures()
    for p in problems:
        print(f"shardcheck: {p}", file=sys.stderr)

    if args.validate:
        n = len(MANIFEST)
        if not problems:
            print(
                f"shardcheck --validate ok: {n} entries, "
                f"{len(GRIDS)} grids, engine coverage complete"
            )
        return 1 if problems else 0

    # the abstract-trace pass needs CPU only — pin it so a
    # TPU-initialized environment cannot make this gate device-bound
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    results = run_all(args.grid, args.entry, verbose=args.verbose)
    failed = [r for r in results if r.status == "fail"]
    skipped = [r for r in results if r.status == "skip"]
    passed = [r for r in results if r.status == "pass"]
    for r in failed:
        print(f"\nFAIL {r.grid}/{r.entry}:\n{r.detail}", file=sys.stderr)
    print(
        f"shardcheck: {len(passed)} passed, {len(failed)} failed, "
        f"{len(skipped)} skipped across {len(args.grid or GRIDS)} grid(s)"
        + (f"; {len(problems)} coverage/validation problem(s)" if problems else "")
    )
    return 1 if (failed or problems) else 0


if __name__ == "__main__":
    sys.exit(main())
