"""shardcheck: device-free SPMD verification of the serve jit surface.

``python -m tools.shardcheck`` abstractly traces every entry in the
registered manifest (tools/shardcheck/manifest.py) with
``jax.eval_shape`` over :class:`jax.sharding.AbstractMesh` grids —
tp2, tp4, dp2×tp2 — on CPU, with zero devices of any mesh shape
attached. What an abstract trace catches *before* a fleet does:

- a typo'd mesh-axis name in ``shard_map`` specs or a collective
  (``KeyError``/``NameError`` at trace time — on a real deployment
  that is a multi-host trace failure at the most expensive moment);
- shapes not divisible by the mesh axes they shard over
  (``ValueError`` from shard_map's evenness check);
- an engine jit signature drifting from its manifest contract
  (output shapes/dtypes, cache-donation structure).

The manifest-coverage check keeps the gate honest: every named jit
site the engine registers through ``_watch``/``_watch_jit`` must have
a manifest entry, so a new jit site cannot ship unverified — adding
one without registering it fails ``python -m tools.shardcheck`` (and
tier-1 CI) until a manifest entry exists. ``--validate`` runs the
offline subset (manifest well-formedness + coverage scan) without
importing JAX. Companion static gate: dtpu-lint DTPU012-014
(docs/reference/lint.md).
"""
