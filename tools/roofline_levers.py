"""Measure the roofline levers from docs/guides/perf-roofline.md.

Round-4 verdict item 2: the levers (8-bit optimizer state, grad
accumulation, the batch size the f32-Adam OOM wall forbade) were
analyzed, not measured. This sweep runs each variant of the 1B train
bench on the visible accelerator and prints one JSON line per variant,
worst-case-isolated in subprocesses so an OOM variant doesn't sink the
sweep. ``tools/tpu_capture.py`` runs it as phase 5 when the tunnel is
up; results land in ``BENCH_TPU_r05_evidence.json``.

Variants (all Llama-3.2-1B, seq 1024, single chip):
  base        batch 8,  f32 Adam, accum 1   — round-3 headline config
  opt8        batch 8,  int8 Adam, accum 1  — halves the optimizer tail
  opt8-b16    batch 16, int8 Adam, accum 1  — the freed ~7.4 GB buys 2x batch
  opt8-accum  batch 32, int8 Adam, accum 4  — amortizes the update 4x
              (microbatch 8 keeps the matmul M; chunked CE keeps logits
              HBM at one chunk so the bigger batch fits)
"""

import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

VARIANTS = [
    ("base", dict(batch=8, opt_bits=32, grad_accum=1, loss_impl="fused")),
    ("opt8", dict(batch=8, opt_bits=8, grad_accum=1, loss_impl="fused")),
    ("opt8-b16", dict(batch=16, opt_bits=8, grad_accum=1, loss_impl="fused")),
    ("opt8-accum", dict(batch=32, opt_bits=8, grad_accum=4, loss_impl="chunked")),
]

CHILD = """
import json, sys
import jax
spec = json.loads(sys.argv[1])
if spec.pop("_force_cpu", False):
    # the axon sitecustomize force-registers the TPU plugin; with the
    # tunnel down its init HANGS, so the parent probes first and tells
    # us to pin cpu (config.update after import — env alone loses)
    jax.config.update("jax_platforms", "cpu")
from dstack_tpu.models import llama
from bench import train_bench
on_tpu = jax.default_backend() in ("tpu", "axon")
cfg = llama.LLAMA_32_1B if on_tpu else llama.LLAMA_TINY
if not on_tpu:
    spec["batch"] = max(spec["batch"] // 4, spec.get("grad_accum", 1))
    r = train_bench(config=cfg, seq=128, steps=3, peak_flops=1e12, **spec)
else:
    r = train_bench(config=cfg, seq=1024, steps=10, **spec)
print(json.dumps(r))
"""


def main() -> int:
    sys.path.insert(0, str(REPO))
    from bench import _tpu_reachable  # one probe definition, bench.py's

    force_cpu = not _tpu_reachable(timeout=90.0)
    if force_cpu:
        # structured flag: the capture layer marks the phase as NOT
        # captured (cpu smoke is not TPU evidence) and the watcher
        # retries it next window
        print(json.dumps({
            "note": "TPU unreachable; cpu smoke numbers only",
            "fallback": True, "platform": "cpu",
        }))
    for name, spec in VARIANTS:
        spec = {**spec, "_force_cpu": force_cpu}
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", CHILD, json.dumps(spec)],
                cwd=REPO, timeout=1500, capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            print(json.dumps({"variant": name, "error": "timeout 1500s"}))
            continue
        lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
        if proc.returncode != 0 or not lines:
            print(json.dumps({
                "variant": name,
                "error": (proc.stderr or proc.stdout).strip()[-300:],
            }))
            continue
        out = json.loads(lines[-1])
        out["variant"] = name
        if force_cpu:
            out["platform"] = "cpu"
        out["wall_s"] = round(time.time() - t0, 1)
        for k in ("mfu", "step_time_s", "tokens_per_sec"):
            if k in out:
                out[k] = round(out[k], 4)
        print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
