#!/usr/bin/env python
"""Thin shim over dtpu-lint rule DTPU001 (blocking-call-in-async).

The checker moved into the unified static-analysis framework
(``tools/dtpu_lint/rules/async_blocking.py``); this entry point keeps
the old script name, the ``check_source(src)`` API, and the exit-code
contract so ``tests/tools/test_check_async_blocking.py`` and the
verify recipes stay green. Prefer ``python -m tools.dtpu_lint``
(optionally ``--rules DTPU001``) for new wiring.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, str(REPO))

from tools.dtpu_lint.core import apply_baseline, load_baseline, run_lint  # noqa: E402
from tools.dtpu_lint.rules.async_blocking import check_source  # noqa: E402,F401


def main() -> int:
    findings = run_lint(REPO, rule_ids=["DTPU001"], project_rules=False)
    diff = apply_baseline(findings, load_baseline())
    for f in diff.new:
        print(f.render(), file=sys.stderr)
    if diff.new:
        print(
            f"\n{len(diff.new)} blocking call(s) inside async def bodies — "
            "move them off the event loop (asyncio.to_thread / "
            "run_in_executor / aiohttp), or append '# blocking: ok' when "
            "genuinely safe.",
            file=sys.stderr,
        )
        return 1
    print("no blocking calls in async bodies (dtpu-lint DTPU001)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
