#!/usr/bin/env python
"""Fail on blocking calls inside ``async def`` bodies of the data plane.

The proxy, gateway, and routing packages ARE the serving hot path: one
``time.sleep`` or sync ``requests.get`` inside a coroutine stalls every
connection on the event loop, and such bugs pass tests (which never
load the loop enough to notice). This AST lint flags, directly inside
``async def`` bodies under ``dstack_tpu/proxy``, ``dstack_tpu/gateway``,
and ``dstack_tpu/routing``:

- ``time.sleep(...)`` (any import alias, incl. ``from time import sleep``)
- any call into the sync ``requests`` / ``urllib.request`` modules
- blocking file I/O: builtin ``open(...)`` and ``Path`` convenience
  methods (``.read_text/.write_text/.read_bytes/.write_bytes``)

Nested *sync* ``def``s inside a coroutine are exempt — the idiom for
work handed to ``run_in_executor``/``asyncio.to_thread``. A line may
opt out with a trailing ``# blocking: ok`` comment (e.g. startup-only
code). Run by tier-1 tests (tests/tools/test_check_async_blocking.py).
"""

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
CHECKED_DIRS = (
    "dstack_tpu/proxy",
    "dstack_tpu/gateway",
    "dstack_tpu/routing",
)
SYNC_HTTP_MODULES = {"requests", "urllib.request"}
PATH_IO_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}
OPT_OUT = "# blocking: ok"


def _module_aliases(tree: ast.AST) -> tuple[dict, set]:
    """(name -> (module, exact), bare function names that are
    ``time.sleep``) collected from the file's imports. ``exact`` means
    the name IS the module object (``import requests``, ``import
    urllib.request as ur``); ``import urllib.request`` only binds the
    ``urllib`` root, so calls through it must spell out the full dotted
    module path to count (``urllib.parse.quote`` is not sync HTTP)."""
    aliases: dict = {}
    sleep_names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in SYNC_HTTP_MODULES or a.name == "time":
                    if a.asname is not None or "." not in a.name:
                        aliases[a.asname or a.name] = (a.name, True)
                    else:
                        aliases[a.name.split(".")[0]] = (a.name, False)
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        sleep_names.add(a.asname or a.name)
            elif node.module in SYNC_HTTP_MODULES or node.module == "urllib":
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    if node.module in SYNC_HTTP_MODULES:
                        aliases[a.asname or a.name] = (full, True)
                    elif full in SYNC_HTTP_MODULES:
                        aliases[a.asname or a.name] = (full, True)
    return aliases, sleep_names


def _dotted(node: ast.AST):
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class _AsyncBodyChecker(ast.NodeVisitor):
    """Walks ONE coroutine body; does not descend into nested sync
    defs (executor-bound work) — nested async defs get their own walk
    from the file-level pass."""

    def __init__(self, aliases, sleep_names, violations, lines):
        self.aliases = aliases
        self.sleep_names = sleep_names
        self.violations = violations
        self.lines = lines

    def visit_FunctionDef(self, node):
        pass  # sync helper inside a coroutine: allowed (executor work)

    def visit_AsyncFunctionDef(self, node):
        pass  # checked separately by the file-level pass

    def visit_Lambda(self, node):
        pass

    def visit_Call(self, node):
        msg = self._classify(node)
        if msg is not None:
            line = self.lines[node.lineno - 1] if node.lineno <= len(self.lines) else ""
            if OPT_OUT not in line:
                self.violations.append((node.lineno, msg))
        self.generic_visit(node)

    def _classify(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "blocking file I/O: open() in async def"
            if func.id in self.sleep_names:
                return "time.sleep() in async def (use asyncio.sleep)"
            bound = self.aliases.get(func.id)
            if bound is not None and (
                bound[0] in SYNC_HTTP_MODULES
                or bound[0].rsplit(".", 1)[0] in SYNC_HTTP_MODULES
            ):
                return f"sync HTTP call ({bound[0]}) in async def"
            return None
        dotted = _dotted(func)
        if dotted is not None:
            root = dotted.split(".")[0]
            bound = self.aliases.get(root)
            if bound is not None:
                module, exact = bound
                if module == "time" and dotted.endswith(".sleep"):
                    return "time.sleep() in async def (use asyncio.sleep)"
                if module in SYNC_HTTP_MODULES and (
                    exact or dotted.startswith(module + ".")
                ):
                    return f"sync HTTP call ({module}) in async def"
        if isinstance(func, ast.Attribute) and func.attr in PATH_IO_METHODS:
            return f"blocking file I/O: .{func.attr}() in async def"
        return None


def check_source(src: str, path: str = "<string>") -> list:
    """→ [(lineno, message)] for one file's source."""
    tree = ast.parse(src, filename=path)
    aliases, sleep_names = _module_aliases(tree)
    lines = src.splitlines()
    violations: list = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            checker = _AsyncBodyChecker(aliases, sleep_names, violations, lines)
            for stmt in node.body:
                checker.visit(stmt)
    return sorted(set(violations))


def main() -> int:
    bad = 0
    files = sorted(
        f for d in CHECKED_DIRS for f in (REPO / d).rglob("*.py")
    )
    for f in files:
        for lineno, msg in check_source(f.read_text(), str(f)):
            print(f"{f.relative_to(REPO)}:{lineno}: {msg}", file=sys.stderr)
            bad += 1
    if bad:
        print(
            f"\n{bad} blocking call(s) inside async def bodies — move "
            "them off the event loop (asyncio.to_thread / run_in_executor "
            "/ aiohttp), or append '# blocking: ok' when genuinely safe.",
            file=sys.stderr,
        )
        return 1
    print(f"no blocking calls in async bodies across {len(files)} files")
    return 0


if __name__ == "__main__":
    sys.exit(main())
