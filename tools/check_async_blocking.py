#!/usr/bin/env python
"""Pure delegating entry point for dtpu-lint rule DTPU001.

Every piece of this checker — the AST walk, the repo scan, the
baseline diff, and the CLI messaging — lives in
``tools/dtpu_lint/rules/async_blocking.py`` (``check_source`` +
``shim_main``). This file only keeps the historical script name and
import path (``check_source``) alive for the verify recipes and old
muscle memory. Prefer ``python -m tools.dtpu_lint --rules DTPU001``.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, str(REPO))

from tools.dtpu_lint.rules.async_blocking import (  # noqa: E402,F401
    check_source,
    shim_main as main,
)

if __name__ == "__main__":
    sys.exit(main())
