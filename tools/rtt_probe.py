"""Tunnel round-trip probe: separates axon-tunnel latency from engine
behavior when serving numbers look dispatch-bound.

Measures, on the live backend (TPU via the tunnel, or CPU fallback):

- ``dispatch_rtt_ms``: host→device→host round trip for a trivial op
  (1-element add, result pulled with ``device_get``) — the floor every
  un-amortized ``Engine.step()`` pays per token.
- ``chained_rtt_ms``: the same op dispatched K=32 times back-to-back
  before a single ``device_get`` — how much of the RTT async dispatch
  pipelining hides (turbo macro-steps rely on this amortization).
- ``h2d_MBps`` / ``d2h_MBps``: 64 MiB transfer bandwidth each way, the
  cost of weight upload and sampled-token readback.

Prints one JSON line; used to annotate serving evidence captured
through the tunnel (decode tok/s at batch B implies a per-step budget
of ``B / tok_s`` seconds — compare against ``dispatch_rtt_ms``).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _med(samples):
    return float(np.median(samples) * 1000.0)


def main() -> None:
    dev = jax.devices()[0]
    one = jnp.ones((), jnp.float32)
    add = jax.jit(lambda x: x + 1)
    add(one).block_until_ready()  # compile

    rtts = []
    for _ in range(20):
        t0 = time.perf_counter()
        jax.device_get(add(one))
        rtts.append(time.perf_counter() - t0)

    chained = []
    for _ in range(10):
        t0 = time.perf_counter()
        x = one
        for _ in range(32):
            x = add(x)
        jax.device_get(x)
        chained.append(time.perf_counter() - t0)

    mb = 64
    buf = np.ones((mb << 20) // 4, np.float32)
    t0 = time.perf_counter()
    dbuf = jax.device_put(buf)
    dbuf.block_until_ready()
    h2d = mb / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    jax.device_get(dbuf)
    d2h = mb / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "tunnel_rtt",
        "value": round(_med(rtts), 2),
        "unit": "ms",
        "extra": {
            "platform": dev.platform,
            "dispatch_rtt_ms": round(_med(rtts), 2),
            "chained32_total_ms": round(_med(chained), 2),
            "chained32_per_step_ms": round(_med(chained) / 32, 3),
            "h2d_MBps": round(h2d, 1),
            "d2h_MBps": round(d2h, 1),
        },
    }))


if __name__ == "__main__":
    main()
