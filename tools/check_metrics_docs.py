#!/usr/bin/env python
"""Fail when an exported metric series is missing from the docs.

Scrapes every metric family name the system can export —

- the HTTP tracing registry (``server/tracing.RequestStats``)
- the serve registry (``serve/metrics.new_serve_registry``)
- the routing registry (``routing/metrics.new_router_registry``)
- the train registry (``train/step.new_train_registry``)
- the DB-backed cluster renderer (``w.sample("name", ...)`` calls in
  ``server/services/prometheus.py``, collected by regex: those names
  are data-driven, not registry-driven)

— and asserts each appears in ``docs/reference/server.md``'s
"Metrics & timeline" section. Run by tier-1 tests
(tests/tools/test_metrics_docs.py), so adding a series without
documenting it fails CI instead of silently drifting.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DOCS = REPO / "docs" / "reference" / "server.md"

if str(REPO) not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, str(REPO))


def collect_metric_names() -> set:
    names: set = set()
    from dstack_tpu.routing.metrics import new_router_registry
    from dstack_tpu.serve.metrics import new_serve_registry
    from dstack_tpu.server.tracing import RequestStats

    names.update(RequestStats().registry.metric_names())
    names.update(new_serve_registry().metric_names())
    names.update(new_router_registry().metric_names())
    try:
        from dstack_tpu.train.step import new_train_registry

        names.update(new_train_registry().metric_names())
    except ImportError as e:
        # jax/optax absent: scrape the registry-construction source
        # instead (a hardcoded fallback list would silently drift when
        # a family is added to new_train_registry)
        print(f"note: train registry parsed from source ({e})", file=sys.stderr)
        step_src = (
            REPO / "dstack_tpu" / "train" / "step.py"
        ).read_text()
        names.update(
            re.findall(
                r'r\.(?:counter|gauge|histogram)\(\s*\n?\s*"([a-z0-9_]+)"',
                step_src,
            )
        )
    renderer = (
        REPO / "dstack_tpu" / "server" / "services" / "prometheus.py"
    ).read_text()
    names.update(re.findall(r'w\.sample\(\s*\n?\s*"([a-z0-9_]+)"', renderer))
    return names


def main() -> int:
    doc = DOCS.read_text()
    missing = sorted(n for n in collect_metric_names() if n not in doc)
    if missing:
        print(
            "exported metrics missing from docs/reference/server.md "
            "(add them to the 'Metrics & timeline' section):",
            file=sys.stderr,
        )
        for n in missing:
            print(f"  {n}", file=sys.stderr)
        return 1
    print(f"docs cover all {len(collect_metric_names())} exported series")
    return 0


if __name__ == "__main__":
    sys.exit(main())
