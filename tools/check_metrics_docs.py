#!/usr/bin/env python
"""Pure delegating entry point for dtpu-lint rule DTPU004 (docs half).

Every piece of this checker — the exporter scrape, the docs diff, and
the CLI messaging — lives in
``tools/dtpu_lint/rules/metric_hygiene.py`` (``collect_metric_names``
+ ``docs_coverage_findings`` + ``shim_main``). This file only keeps
the historical script name and ``collect_metric_names()`` signature
alive. Prefer ``python -m tools.dtpu_lint --rules DTPU004``.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, str(REPO))

from tools.dtpu_lint.rules.metric_hygiene import (  # noqa: E402
    collect_metric_names as _collect,
    docs_coverage_findings,  # noqa: F401
    shim_main as main,
)


def collect_metric_names() -> set:
    return _collect(REPO)


if __name__ == "__main__":
    sys.exit(main())
