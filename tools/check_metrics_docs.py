#!/usr/bin/env python
"""Thin shim over dtpu-lint rule DTPU004 (metric docs coverage).

The checker moved into the unified static-analysis framework
(``tools/dtpu_lint/rules/metric_hygiene.py``); this entry point keeps
the old script name, the ``collect_metric_names()`` API, and the
exit-code contract so ``tests/tools/test_metrics_docs.py`` and the
verify recipes stay green. Prefer ``python -m tools.dtpu_lint``
(optionally ``--rules DTPU004``) for new wiring.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, str(REPO))

from tools.dtpu_lint.rules.metric_hygiene import (  # noqa: E402
    docs_coverage_findings,
    collect_metric_names as _collect,
)


def collect_metric_names() -> set:
    return _collect(REPO)


def main() -> int:
    missing = docs_coverage_findings(REPO)
    if missing:
        print(
            "exported metrics missing from docs/reference/server.md "
            "(add them to the 'Metrics & timeline' section):",
            file=sys.stderr,
        )
        for f in missing:
            print(f"  {f.message}", file=sys.stderr)
        return 1
    print(
        f"docs cover all {len(collect_metric_names())} exported series "
        "(dtpu-lint DTPU004)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
