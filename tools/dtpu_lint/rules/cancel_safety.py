"""DTPU010: cancellation-safety of tracked resource acquisitions.

asyncio cancellation can land on ANY ``await``. An async function that
acquires a tracked resource imperatively — an entity-lock claim
(``try_claim``), a QoS bucket charge (``try_acquire``), a pool/lock
``acquire``, a durable wakeup claim (``wakeups.claim``), or an
inflight/outstanding counter bump — and then reaches an ``await``
before releasing it will LEAK the resource when the task is cancelled
between the two, unless the release runs in a ``try/finally`` (or the
acquisition rides a context manager, which is the preferred idiom and
is never flagged).

Leaked claims wedge entities until lease expiry; a stranded
``inflight`` gauge makes a drained replica look busy forever (the
autoscaler and drain logic both key on it); an uncharged-back bucket
silently shrinks a tenant's budget. All were near-misses in PR 6/7
review.

The rule matches acquire/release pairs on the same receiver
(``ls.try_claim`` ↔ ``ls.release``, ``pool.acquire`` ↔
``pool.release``, ``bucket.try_acquire`` ↔ ``bucket.refund``,
``self._inflight += 1`` ↔ ``-= 1``) and flags:

- an acquisition with awaits after it and **no release on the path**;
- a release that is **not inside a finally** while awaits occur
  between acquire and release.

Lease-style acquisitions that are crash-safe BY DESIGN (redelivery on
lease expiry) opt out at the acquisition line with
``# dtpu: noqa[DTPU010] <why>``.
"""

from typing import Iterable, Optional

from tools.dtpu_lint.core import Finding, ProjectRule, register
from tools.dtpu_lint.flow import ACQUIRE_RELEASE, get_flow, report_paths

#: DTPU010 reports beyond the shared flow scope: the serve data
#: plane's async edge, whose slot-acquire / deadline-abort / QoS-refund
#: paths (PR 10) carry exactly the tracked-resource shapes this rule
#: exists for. Only this rule widens — DTPU008/009/011 keep the
#: control-plane scope (the serve process has no DB pools or
#: cross-shard locks to analyze).
EXTRA_REPORT_PATHS = frozenset({"dstack_tpu/serve/openai_server.py"})


def _receiver(callee: str) -> str:
    return callee.rsplit(".", 1)[0] if "." in callee else ""


def _final(callee: str) -> str:
    return callee.rsplit(".", 1)[-1]


def _is_suspension(ev) -> bool:
    """Events where cancellation can land: awaits, awaited context
    enters, and yields. A synchronous ``with`` enter is not a
    suspension point — a sync critical section between acquire and
    release is cancellation-safe."""
    k = ev["k"]
    if k in ("await", "yield"):
        return True
    return k == "enter" and bool(ev.get("awaited"))


def _is_wakeup_claim(flow, fi, callee: str) -> bool:
    if _final(callee) != "claim":
        return False
    return any(
        t.path.endswith("services/wakeups.py") and t.summary["name"] == "claim"
        for t in flow.callee_facts(fi, callee)
    )


@register
class CancellationSafetyRule(ProjectRule):
    id = "DTPU010"
    name = "resource acquisition without cancellation-safe release"

    def check_project(self, repo) -> Iterable[Finding]:
        flow = get_flow(repo)
        scope = report_paths(repo) | EXTRA_REPORT_PATHS
        for fi in flow.functions():
            if fi.path not in scope or not fi.summary["is_async"]:
                continue
            yield from self._check_function(flow, fi)

    def _check_function(self, flow, fi):
        f = fi.summary
        events = f["events"]
        qual = f["qual"]
        matched_releases: set = set()
        for i, ev in enumerate(events):
            acq = self._acquire_of(flow, fi, ev)
            if acq is None or ev.get("fin"):
                continue
            if "DTPU010" in set(ev.get("noqa", ())):
                continue
            release_names, receiver, label = acq
            rel_idx: Optional[int] = None
            for j in range(i + 1, len(events)):
                if j in matched_releases:
                    continue
                if self._releases(events[j], release_names, receiver):
                    rel_idx = j
                    break
            if rel_idx is None:
                if any(_is_suspension(e) for e in events[i + 1:]):
                    yield Finding(
                        "DTPU010",
                        fi.path,
                        ev["line"],
                        f"{label} acquired with awaits following but no "
                        f"release on this path — task cancellation leaks "
                        f"it [in {qual}]",
                    )
                continue
            matched_releases.add(rel_idx)
            rel = events[rel_idx]
            if rel.get("fin"):
                continue  # try/finally: cancellation-safe
            if any(_is_suspension(e) for e in events[i + 1: rel_idx]):
                yield Finding(
                    "DTPU010",
                    fi.path,
                    ev["line"],
                    f"{label} released outside try/finally with awaits "
                    f"in between — cancellation at any of them leaks it "
                    f"[in {qual}]",
                )

    def _acquire_of(self, flow, fi, ev):
        """(release-names, receiver, label) when ev acquires a tracked
        resource; None otherwise. ``enter`` events are context-managed
        and inherently safe."""
        k = ev["k"]
        if k == "aug" and ev["op"] == "+":
            return (("-",), ev["target"], f"counter {ev['target']} bump")
        if k not in ("await", "call") or not ev.get("callee"):
            return None
        callee = ev["callee"]
        final = _final(callee)
        if final in ACQUIRE_RELEASE:
            return (
                ACQUIRE_RELEASE[final],
                _receiver(callee),
                f"resource ({callee})",
            )
        if _is_wakeup_claim(flow, fi, callee):
            return (
                ("ack", "release"),
                _receiver(callee),
                f"wakeup claim ({callee})",
            )
        return None

    @staticmethod
    def _releases(ev, release_names, receiver) -> bool:
        if ev["k"] == "aug":
            return ev["op"] == "-" and ev["target"] == receiver
        if ev["k"] not in ("await", "call") or not ev.get("callee"):
            return False
        callee = ev["callee"]
        return _final(callee) in release_names and (
            _receiver(callee) == receiver or not receiver
        )
