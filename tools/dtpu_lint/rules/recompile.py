"""DTPU003: recompile hazards around ``jax.jit``.

XLA compiles one variant per (shape, static-arg) signature. Two
patterns turn that into an unbounded compile storm that passes every
unit test (tests use one or two shapes) and melts down under real
traffic:

- **jit inside a loop** — ``jax.jit(...)`` in a ``for``/``while`` body
  re-traces every iteration unless the result is memoized; even
  memoized, each iteration pays Python-side wrapper construction.
- **jit cache keyed by a caller-supplied value** — the
  ``self._fns[key] = jax.jit(...)`` memoization idiom is only bounded
  if every caller buckets the key (this repo's contract: powers of
  two, giving a log2 grid of variants — see
  ``InferenceEngine.prefill_wave``). The rule cannot see across
  functions, so every such assignment is flagged; a site whose
  callers provably bucket opts out with
  ``# dtpu: noqa[DTPU003] <which caller buckets and how>`` — the
  pragma (not folklore) then documents the contract, and a new
  unbucketed caller is a reviewable diff on the bucketing sites.

A ``functools.lru_cache(maxsize=N)``-decorated factory is the bounded
alternative for caller-keyed jits (the embeddings endpoint's pattern).
"""

import ast

from tools.dtpu_lint.core import FileRule, Finding, register


def _jax_names(tree: ast.AST) -> set:
    """Local names bound to the jax module (``import jax``,
    ``import jax as _jax``)."""
    names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    names.add(a.asname or "jax")
    return names


def _is_jit_call(node: ast.AST, jax_names: set) -> bool:
    """``jax.jit(...)`` / ``jax.pmap(...)`` through any jax alias."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ("jit", "pmap")
        and isinstance(f.value, ast.Name)
        and f.value.id in jax_names
    )


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _FnChecker(ast.NodeVisitor):
    """Walks ONE function body looking for loop-jits and cache-key
    assignments; nested defs get their own walk from the file pass."""

    def __init__(self, fn, jax_names, relpath, findings):
        self.fn = fn
        self.jax_names = jax_names
        self.relpath = relpath
        self.findings = findings
        params = {
            a.arg
            for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            )
            if a.arg not in ("self", "cls")
        }
        # taint locals derived from parameters (`key = (cl, start)`)
        # so the engine's two-line memoization idiom is still seen;
        # iterate to a fixpoint for chained assignments
        tainted = set(params)
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and _names_in(node.value) & tainted:
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            changed = True
        self.params = tainted
        self._loop_depth = 0

    def visit_FunctionDef(self, node):
        pass  # separate walk

    visit_AsyncFunctionDef = visit_FunctionDef

    def _visit_loop(self, node):
        self._loop_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Assign(self, node):
        jit_value = _is_jit_call(node.value, self.jax_names)
        for target in node.targets:
            if jit_value and isinstance(target, ast.Subscript):
                key_names = _names_in(target.slice) & self.params
                if key_names:
                    self.findings.append(
                        Finding(
                            "DTPU003",
                            self.relpath,
                            node.lineno,
                            "jit cache keyed by caller-supplied "
                            f"value(s) {sorted(key_names)} in "
                            f"{self.fn.name}(): unbounded unless every "
                            "caller buckets the key (powers of two); "
                            "noqa with the bucketing call sites, or "
                            "use functools.lru_cache(maxsize=N)",
                        )
                    )
        self.generic_visit(node)

    def visit_Call(self, node):
        if self._loop_depth > 0 and _is_jit_call(node, self.jax_names):
            self.findings.append(
                Finding(
                    "DTPU003",
                    self.relpath,
                    node.lineno,
                    f"jax.{node.func.attr}() inside a loop in "
                    f"{self.fn.name}(): re-traces/rebuilds per iteration "
                    "— hoist it or memoize with a bounded key",
                )
            )
        self.generic_visit(node)


@register
class RecompileRule(FileRule):
    id = "DTPU003"
    name = "recompile hazard (jit-in-loop, unbucketed jit cache key)"
    scope = (
        "dstack_tpu/serve/*.py",
        "dstack_tpu/ops/*.py",
        "dstack_tpu/train/*.py",
        "dstack_tpu/models/*.py",
    )

    def check(self, tree, src, relpath, repo):
        jax_names = _jax_names(tree)
        if not jax_names:
            return []
        findings: list = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _FnChecker(node, jax_names, relpath, findings)
                for stmt in node.body:
                    checker.visit(stmt)
        return findings
