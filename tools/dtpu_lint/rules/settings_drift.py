"""DTPU005: settings drift — undocumented ``DTPU_*`` env reads.

``server/settings.py`` is the documented configuration surface; env
vars read anywhere else accumulate silently until nobody can list what
actually configures a deployment. The agent, serve, and backend
processes legitimately read a handful of ``DTPU_*`` vars directly
(they run on job hosts and must not import server settings), so the
contract is *documented, not necessarily centralized*: every
``os.getenv("DTPU_…")`` / ``os.environ["DTPU_…"]`` /
``os.environ.get("DTPU_…")`` outside ``server/settings.py`` must name
a variable documented in ``docs/reference/server.md`` (operator
surface) or ``docs/reference/testing.md`` (test-infra switches).
An undocumented read fails the gate — centralize it into
``server/settings.py`` or add it to the docs table.
"""

import ast
import re
from functools import lru_cache
from pathlib import Path

from tools.dtpu_lint.core import FileRule, Finding, register

DOC_FILES = (
    Path("docs") / "reference" / "server.md",
    Path("docs") / "reference" / "testing.md",
)

_VAR_RE = re.compile(r"DTPU_[A-Z0-9_]+")


@lru_cache(maxsize=4)
def documented_vars(repo: Path) -> frozenset:
    names: set = set()
    for rel in DOC_FILES:
        p = repo / rel
        if p.exists():
            names.update(_VAR_RE.findall(p.read_text()))
    return frozenset(names)


def _env_read_var(node: ast.AST):
    """The DTPU_* var name a call/subscript reads, or None.

    Matches ``os.getenv("X", ...)``, ``os.environ.get("X", ...)``,
    ``os.environ["X"]``, and the same through ``environ`` imported
    from os (``from os import environ, getenv``)."""

    def _const_var(expr) -> str:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            m = _VAR_RE.fullmatch(expr.value)
            if m:
                return expr.value
        return None

    def _is_environ(expr) -> bool:
        if isinstance(expr, ast.Attribute) and expr.attr == "environ":
            return isinstance(expr.value, ast.Name) and expr.value.id == "os"
        return isinstance(expr, ast.Name) and expr.id == "environ"

    if isinstance(node, ast.Call) and node.args:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "getenv" and isinstance(f.value, ast.Name) and f.value.id == "os":
                return _const_var(node.args[0])
            if f.attr == "get" and _is_environ(f.value):
                return _const_var(node.args[0])
        elif isinstance(f, ast.Name) and f.id == "getenv":
            return _const_var(node.args[0])
    elif (
        isinstance(node, ast.Subscript)
        and isinstance(node.ctx, ast.Load)  # a write is not drift
        and _is_environ(node.value)
    ):
        return _const_var(node.slice)
    return None


@register
class SettingsDriftRule(FileRule):
    id = "DTPU005"
    name = "settings drift (undocumented DTPU_* env read)"
    scope = ("dstack_tpu/**/*.py",)

    def applies(self, relpath: str) -> bool:
        if relpath == "dstack_tpu/server/settings.py":
            return False  # THE settings surface
        return super().applies(relpath)

    def check(self, tree, src, relpath, repo):
        documented = documented_vars(repo)
        for node in ast.walk(tree):
            var = _env_read_var(node)
            if var is not None and var not in documented:
                yield Finding(
                    "DTPU005",
                    relpath,
                    node.lineno,
                    f"env var {var} read outside server/settings.py and "
                    "not documented in docs/reference/server.md — "
                    "centralize it in settings or document it",
                )
