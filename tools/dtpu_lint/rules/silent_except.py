"""DTPU006: silent broad except in reconciliation/routing code.

The background loops and the routing layer are exactly where the fault
layer (:mod:`dstack_tpu.faults`) injects failures — and where a bare
``except Exception: pass`` turns an injected (or real) fault into
nothing: the chaos suite would green-light an invariant the code never
actually survived, and production failures would vanish without a log
line.

The rule flags ``except Exception:`` / bare ``except:`` handlers whose
body neither logs (no ``logger``/``logging``/``log`` call) nor
re-raises. Narrow the exception to what the code actually expects, or
add structured logging (the failure's identity and subject, not just
"something went wrong"). A handler that legitimately must stay silent
takes a ``# dtpu: noqa[DTPU006] <why>`` pragma.

Scope: ``server/background/`` and ``routing/`` — the planes the chaos
suite drives. Grandfathered findings live in the shrink-only baseline.
"""

import ast
from typing import Iterable

from tools.dtpu_lint.core import FileRule, Finding, register

_LOG_NAMES = {"logger", "logging", "log"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, ast.Name) and t.id in ("Exception", "BaseException"):
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
            for e in t.elts
        )
    return False


def _handles_visibly(handler: ast.ExceptHandler) -> bool:
    """True when the body logs or re-raises (incl. raising a new
    error — the failure stays visible to the caller either way)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Name) and base.id in _LOG_NAMES:
                return True
    return False


def _enclosing_function(tree: ast.AST, handler: ast.ExceptHandler) -> str:
    best = "<module>"
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (
                node.lineno <= handler.lineno
                and handler.lineno <= (node.end_lineno or node.lineno)
            ):
                best = node.name  # innermost wins: walk yields outer first
    return best


@register
class SilentBroadExceptRule(FileRule):
    id = "DTPU006"
    name = "silent broad except in background/routing code"
    scope = (
        "dstack_tpu/server/background/**/*.py",
        "dstack_tpu/routing/**/*.py",
    )

    def check(self, tree, src, relpath, repo) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _handles_visibly(node):
                continue
            fn = _enclosing_function(tree, node)
            yield Finding(
                "DTPU006",
                relpath,
                node.lineno,
                f"silent broad except in {fn}: an injected or real fault "
                "vanishes here — log it (with the subject's identity) or "
                "narrow the exception",
            )
