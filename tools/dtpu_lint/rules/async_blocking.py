"""DTPU001: blocking calls inside ``async def`` on the data plane.

The proxy, gateway, and routing packages ARE the serving hot path: one
``time.sleep`` or sync ``requests.get`` inside a coroutine stalls every
connection on the event loop, and such bugs pass tests (which never
load the loop enough to notice). Flagged, directly inside ``async def``
bodies:

- ``time.sleep(...)`` (any import alias, incl. ``from time import sleep``)
- any call into the sync ``requests`` / ``urllib.request`` modules
- blocking file I/O: builtin ``open(...)`` and ``Path`` convenience
  methods (``.read_text/.write_text/.read_bytes/.write_bytes``)

Nested *sync* ``def``s inside a coroutine are exempt — the idiom for
work handed to ``run_in_executor``/``asyncio.to_thread``. Opt-outs:
the framework pragma ``# dtpu: noqa[DTPU001] <reason>`` or the legacy
``# blocking: ok`` trailer (kept so pre-framework exemptions and the
muscle memory around them stay valid).

Migrated from ``tools/check_async_blocking.py`` (PR 3), which remains
as a thin shim over this rule.
"""

import ast

from tools.dtpu_lint.core import FileRule, Finding, register

SYNC_HTTP_MODULES = {"requests", "urllib.request"}
PATH_IO_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}
LEGACY_OPT_OUT = "# blocking: ok"


def _module_aliases(tree: ast.AST) -> tuple:
    """(name -> (module, exact), bare names bound to ``time.sleep``)
    collected from the file's imports. ``exact`` means the name IS the
    module object (``import requests``, ``import urllib.request as
    ur``); ``import urllib.request`` only binds the ``urllib`` root, so
    calls through it must spell out the full dotted module path to
    count (``urllib.parse.quote`` is not sync HTTP)."""
    aliases: dict = {}
    sleep_names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in SYNC_HTTP_MODULES or a.name == "time":
                    if a.asname is not None or "." not in a.name:
                        aliases[a.asname or a.name] = (a.name, True)
                    else:
                        aliases[a.name.split(".")[0]] = (a.name, False)
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        sleep_names.add(a.asname or a.name)
            elif node.module in SYNC_HTTP_MODULES or node.module == "urllib":
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    if node.module in SYNC_HTTP_MODULES or full in SYNC_HTTP_MODULES:
                        aliases[a.asname or a.name] = (full, True)
    return aliases, sleep_names


def _dotted(node: ast.AST):
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class _AsyncBodyChecker(ast.NodeVisitor):
    """Walks ONE coroutine body; does not descend into nested sync
    defs (executor-bound work) — nested async defs get their own walk
    from the file-level pass."""

    def __init__(self, aliases, sleep_names, violations, lines):
        self.aliases = aliases
        self.sleep_names = sleep_names
        self.violations = violations
        self.lines = lines

    def visit_FunctionDef(self, node):
        pass  # sync helper inside a coroutine: allowed (executor work)

    def visit_AsyncFunctionDef(self, node):
        pass  # checked separately by the file-level pass

    def visit_Lambda(self, node):
        pass

    def visit_Call(self, node):
        msg = self._classify(node)
        if msg is not None:
            line = (
                self.lines[node.lineno - 1]
                if node.lineno <= len(self.lines)
                else ""
            )
            if LEGACY_OPT_OUT not in line:
                self.violations.append((node.lineno, msg))
        self.generic_visit(node)

    def _classify(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "blocking file I/O: open() in async def"
            if func.id in self.sleep_names:
                return "time.sleep() in async def (use asyncio.sleep)"
            bound = self.aliases.get(func.id)
            if bound is not None and (
                bound[0] in SYNC_HTTP_MODULES
                or bound[0].rsplit(".", 1)[0] in SYNC_HTTP_MODULES
            ):
                return f"sync HTTP call ({bound[0]}) in async def"
            return None
        dotted = _dotted(func)
        if dotted is not None:
            root = dotted.split(".")[0]
            bound = self.aliases.get(root)
            if bound is not None:
                module, exact = bound
                if module == "time" and dotted.endswith(".sleep"):
                    return "time.sleep() in async def (use asyncio.sleep)"
                if module in SYNC_HTTP_MODULES and (
                    exact or dotted.startswith(module + ".")
                ):
                    return f"sync HTTP call ({module}) in async def"
        if isinstance(func, ast.Attribute) and func.attr in PATH_IO_METHODS:
            return f"blocking file I/O: .{func.attr}() in async def"
        return None


def check_source(src: str, path: str = "<string>") -> list:
    """→ [(lineno, message)] for one file's source (the shim API kept
    for tools/check_async_blocking.py and its tier-1 test)."""
    tree = ast.parse(src, filename=path)
    aliases, sleep_names = _module_aliases(tree)
    lines = src.splitlines()
    violations: list = []
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            checker = _AsyncBodyChecker(aliases, sleep_names, violations, lines)
            for stmt in node.body:
                checker.visit(stmt)
    return sorted(set(violations))


@register
class AsyncBlockingRule(FileRule):
    id = "DTPU001"
    name = "blocking call inside async def (data plane)"
    scope = (  # glob_match's **/ spans zero dirs: top-level included
        "dstack_tpu/proxy/**/*.py",
        "dstack_tpu/gateway/**/*.py",
        "dstack_tpu/routing/**/*.py",
        # the open-loop driver shares the event loop with the stack it
        # measures: a blocking call here distorts every latency number
        "dstack_tpu/loadgen/**/*.py",
    )

    def check(self, tree, src, relpath, repo):
        for lineno, msg in check_source(src, relpath):
            yield Finding(self.id, relpath, lineno, msg)


def shim_main() -> int:
    """The whole CLI of tools/check_async_blocking.py (a pure
    delegating entry point since the shim fold): run DTPU001
    repo-wide against the baseline, old exit-code contract intact."""
    import sys

    from tools.dtpu_lint.core import (
        REPO,
        apply_baseline,
        load_baseline,
        run_lint,
    )

    findings = run_lint(REPO, rule_ids=["DTPU001"], project_rules=False)
    diff = apply_baseline(findings, load_baseline())
    for f in diff.new:
        print(f.render(), file=sys.stderr)
    if diff.new:
        print(
            f"\n{len(diff.new)} blocking call(s) inside async def bodies — "
            "move them off the event loop (asyncio.to_thread / "
            "run_in_executor / aiohttp), or append '# blocking: ok' when "
            "genuinely safe.",
            file=sys.stderr,
        )
        return 1
    print("no blocking calls in async bodies (dtpu-lint DTPU001)")
    return 0
