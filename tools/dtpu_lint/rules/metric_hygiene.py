"""DTPU004: metric hygiene — docs coverage + bounded label values.

Two halves, one invariant: every exported series is documented, and no
label can grow without bound.

**Docs coverage** (project-wide, absorbs ``tools/check_metrics_docs.py``
from PR 1): scrapes every metric family name the system can export —
the HTTP tracing registry, the serve/routing/train registry factories,
and the DB-backed cluster renderer's ``w.sample("name", ...)`` calls —
and fails when one is missing from ``docs/reference/server.md``'s
"Metrics & timeline" section.

**Label hygiene** (per file, repo-wide): label values passed to
``.inc(value, *labels)`` / ``.set(value, *labels)`` /
``.observe(value, *labels)`` must be literals or come from a bounded
enum (``x.state.value``-style attribute access). A request-derived
string — an f-string, concatenation, ``.format()``, ``str(...)`` or
any call result — mints a new series per distinct value; the obs
registry's cardinality cap turns that into a silent ``<truncated>``
collapse instead of an OOM, but the series is still garbage. Bare
names are allowed (typically a loop over a bounded state dict); the
rule catches the *construction* of unbounded values at the call site.

**Span-name hygiene** (same FileRule): the name passed to
``tracing.span(...)`` (``dstack_tpu.obs.tracing``) must be a string
LITERAL — span names are bounded-cardinality identifiers exactly like
metric label names; a request-derived name would flood every grouping
consumer of ``/debug/traces``. Span *attrs* are free-form (and
truncated by the tracer).
"""

import ast
import re
import sys
from pathlib import Path

from tools.dtpu_lint.core import FileRule, Finding, ProjectRule, register

_LABEL_METHODS = {"inc", "set", "observe"}

DOCS_REL = Path("docs") / "reference" / "server.md"


def _label_problem(arg: ast.AST):
    """Why this label-value expression is unbounded, or None when ok."""
    if isinstance(arg, ast.JoinedStr):
        return "an f-string"
    if isinstance(arg, ast.BinOp):
        return "a string-building expression"
    if isinstance(arg, ast.Call):
        f = arg.func
        if isinstance(f, ast.Attribute) and f.attr == "format":
            return ".format()"
        if isinstance(f, ast.Name) and f.id == "str":
            return "str(...)"
        return "a call result"
    return None


def check_label_source(src: str, relpath: str = "<string>") -> list:
    """→ Findings for unbounded metric label values in one file."""
    tree = ast.parse(src, filename=relpath)
    findings: list = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _LABEL_METHODS
            and len(node.args) >= 2
        ):
            continue
        # args[0] is the value; the rest are label values
        for arg in node.args[1:]:
            why = _label_problem(arg)
            if why is not None:
                findings.append(
                    Finding(
                        "DTPU004",
                        relpath,
                        node.lineno,
                        f"metric label value built from {why}: labels "
                        "must be literals or bounded-enum attributes "
                        "(request-derived labels mint unbounded series)",
                    )
                )
    return findings


def check_span_name_source(src: str, relpath: str = "<string>") -> list:
    """→ Findings for non-literal span names in one file. Matches
    ``<x>tracing.span(...)`` attribute calls (the module-level factory
    under any alias ending in ``tracing``) AND bare calls through a
    ``from dstack_tpu.obs.tracing import span [as alias]`` binding;
    ``Tracer.span``'s own definition and no-op rebinding are
    declarations, not calls."""
    tree = ast.parse(src, filename=relpath)
    # names the span factory was imported under directly
    span_aliases: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and (
            node.module or ""
        ).endswith("tracing"):
            for a in node.names:
                if a.name == "span":
                    span_aliases.add(a.asname or a.name)
    findings: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_factory = (
            isinstance(f, ast.Attribute)
            and f.attr == "span"
            and isinstance(f.value, ast.Name)
            and f.value.id.endswith("tracing")
        ) or (isinstance(f, ast.Name) and f.id in span_aliases)
        if not is_factory or not node.args:
            continue
        name = node.args[0]
        if isinstance(name, ast.Constant) and isinstance(name.value, str):
            continue
        findings.append(
            Finding(
                "DTPU004",
                relpath,
                node.lineno,
                "span name passed to tracing.span() must be a string "
                "literal: span names are bounded-cardinality "
                "identifiers, same rationale as metric label values "
                "(put request-derived context in span attrs instead)",
            )
        )
    return findings


@register
class MetricLabelRule(FileRule):
    id = "DTPU004"
    name = "metric hygiene (bounded label values + literal span names)"
    scope = ("dstack_tpu/**/*.py",)

    def check(self, tree, src, relpath, repo):
        return check_label_source(src, relpath) + check_span_name_source(
            src, relpath
        )


# ---------------------------------------------------------------------------
# docs coverage (project half)
# ---------------------------------------------------------------------------


def collect_metric_names(repo: Path) -> set:
    """Every metric family name the system can export."""
    if str(repo) not in sys.path:  # runnable from anywhere
        sys.path.insert(0, str(repo))
    names: set = set()
    from dstack_tpu.loadgen.metrics import new_loadgen_registry
    from dstack_tpu.obs.boot import new_boot_registry
    from dstack_tpu.obs.flight import new_flight_registry
    from dstack_tpu.obs.slo import new_slo_registry
    from dstack_tpu.obs.tracing import new_trace_registry
    from dstack_tpu.qos.metrics import new_qos_registry
    from dstack_tpu.routing.metrics import new_router_registry
    from dstack_tpu.serve.metrics import new_serve_registry
    from dstack_tpu.server.services.wakeups import new_reconcile_registry
    from dstack_tpu.server.sentry_compat import RequestStats
    from dstack_tpu.utils.retry import new_retry_registry

    names.update(RequestStats().registry.metric_names())
    names.update(new_serve_registry().metric_names())
    names.update(new_router_registry().metric_names())
    names.update(new_retry_registry().metric_names())
    names.update(new_qos_registry().metric_names())
    names.update(new_reconcile_registry().metric_names())
    names.update(new_loadgen_registry().metric_names())
    names.update(new_trace_registry().metric_names())
    names.update(new_slo_registry().metric_names())
    names.update(new_flight_registry().metric_names())
    names.update(new_boot_registry().metric_names())
    try:
        from dstack_tpu.train.step import new_train_registry

        names.update(new_train_registry().metric_names())
    except ImportError as e:
        # jax/optax absent: scrape the registry-construction source
        # instead (a hardcoded fallback list would silently drift when
        # a family is added to new_train_registry)
        print(f"note: train registry parsed from source ({e})", file=sys.stderr)
        step_src = (repo / "dstack_tpu" / "train" / "step.py").read_text()
        names.update(
            re.findall(
                r'r\.(?:counter|gauge|histogram)\(\s*\n?\s*"([a-z0-9_]+)"',
                step_src,
            )
        )
    renderer = (
        repo / "dstack_tpu" / "server" / "services" / "prometheus.py"
    ).read_text()
    names.update(re.findall(r'w\.sample\(\s*\n?\s*"([a-z0-9_]+)"', renderer))
    return names


def docs_coverage_findings(repo: Path) -> list:
    doc = (repo / DOCS_REL).read_text()
    return [
        Finding(
            "DTPU004",
            DOCS_REL.as_posix(),
            1,
            f"exported metric series `{n}` is missing from the "
            "'Metrics & timeline' section",
        )
        for n in sorted(collect_metric_names(repo))
        if n not in doc
    ]


@register
class MetricDocsRule(ProjectRule):
    id = "DTPU004-DOCS"
    name = "metric hygiene (every exported series documented)"

    def check_project(self, repo):
        return docs_coverage_findings(repo)


def shim_main() -> int:
    """The whole CLI of tools/check_metrics_docs.py (a pure delegating
    entry point since the shim fold): docs-coverage scan with the old
    exit-code contract."""
    from tools.dtpu_lint.core import REPO

    missing = docs_coverage_findings(REPO)
    if missing:
        print(
            "exported metrics missing from docs/reference/server.md "
            "(add them to the 'Metrics & timeline' section):",
            file=sys.stderr,
        )
        for f in missing:
            print(f"  {f.message}", file=sys.stderr)
        return 1
    print(
        f"docs cover all {len(collect_metric_names(REPO))} exported series "
        "(dtpu-lint DTPU004)"
    )
    return 0
