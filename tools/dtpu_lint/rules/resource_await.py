"""DTPU008: exclusive resource held across a blocking await.

The PR 7 pool deadlock, generalized. Holding an exclusive resource —
a DB transaction (the sqlite engine's single-writer lock; a pooled
connection on Postgres), a bounded-pool connection, a TokenBucket
charge, an engine slot — while awaiting something *unbounded* hands
the event loop a classic resource-ordering hazard:

- awaiting **lock acquisition** (entity locks, advisory claims) while
  holding the resource serializes every other holder behind a lock
  queue of unknown depth;
- awaiting an **agent/network RPC** pins the resource for a remote
  round trip (seconds under fault injection, forever under a hang);
- awaiting anything that transitively reaches a **retry_async site**
  pins it for a whole jittered backoff schedule;
- awaiting anything that **re-acquires from the same pool** the held
  connection came from is the literal PR 7 shape: enough concurrent
  holders exhaust the pool and every body blocks on itself — a hard
  deadlock no unit test reaches (15 claimants × 8 connections did it
  at the 1500-job bench).

Tracked held resources: ``db.transaction()`` contexts (and any
asynccontextmanager that transitively holds a pool connection across
its yield), advisory-claim contexts (pool-identity checks only), and
the ctx-held forms of a QoS bucket charge / engine slot
(``async with bucket.charged(...)`` / ``engine.hold_slot(...)`` —
see ``flow.BUCKET_HOLD_NAMES``/``SLOT_HOLD_NAMES``; the imperative
``try_acquire``/``refund`` style is DTPU010's domain).

Findings are interprocedural: ``async with db.transaction():`` +
``await helper()`` is flagged when ``helper`` reaches an RPC three
calls down. Opt-outs at the await line (``# dtpu: noqa[DTPU008]
<why>``) — or at the *acquisition source* for reentrancy-aware code
(``PostgresDatabase._conn`` diverts to the held tx connection via a
contextvar; its pragma silences every transitive report).
"""

from typing import Iterable

from tools.dtpu_lint.core import Finding, ProjectRule, register
from tools.dtpu_lint.flow import (
    BLOCKING_LOCK_NAMES,
    CLAIM_NAMES,
    RETRY_NAMES,
    _is_net_io,
    _pool_token,
    get_flow,
    report_paths,
)

#: held-resource kinds that make ANY blocking await a finding (the
#: single-writer tx lock is the most contended object in the server)
_STRICT_KINDS = frozenset({"tx", "bucket", "slot"})


def _classify_await(flow, fi, callee: str) -> list:
    """Blocking classes an awaited call belongs to."""
    out = []
    final = callee.rsplit(".", 1)[-1]
    targets = flow.callee_facts(fi, callee)
    if final in CLAIM_NAMES or final in BLOCKING_LOCK_NAMES or any(
        t.lock_reach for t in targets
    ):
        out.append("lock acquisition")
    if _is_net_io(callee) or any(t.reaches_rpc for t in targets):
        out.append("network RPC")
    if final in RETRY_NAMES or any(t.reaches_retry for t in targets):
        out.append("a retry/backoff loop")
    return out


def _await_pool_tokens(flow, fi, callee: str) -> set:
    toks = set()
    direct = _pool_token(callee, fi.summary["cls"])
    if direct:
        toks.add(direct)
    for t in flow.callee_facts(fi, callee):
        toks |= set(t.pool_tokens)
    return toks


@register
class ResourceAcrossAwaitRule(ProjectRule):
    id = "DTPU008"
    name = "exclusive resource held across blocking await"

    def check_project(self, repo) -> Iterable[Finding]:
        flow = get_flow(repo)
        scope = report_paths(repo)
        seen = set()
        for fi in flow.functions():
            if fi.path not in scope or not fi.summary["is_async"]:
                continue
            yield from self._check_function(flow, fi, seen)

    def _check_function(self, flow, fi, seen):
        f = fi.summary
        held: list = []  # (callee, frozenset of (kind, token) entries)
        for ev in f["events"]:
            k = ev["k"]
            callee = ev.get("callee")
            if k == "exit":
                if held and held[-1][0] == callee:
                    held.pop()
                continue
            if k not in ("enter", "await") or not callee:
                continue
            # classify this await against what is CURRENTLY held —
            # before an enter installs its own resources
            if held:
                yield from self._check_await(flow, fi, ev, held, seen)
            if k == "enter":
                held.append((callee, frozenset(flow._direct_hold(fi, ev))))

    def _check_await(self, flow, fi, ev, held, seen):
        callee = ev["callee"]
        final = callee.rsplit(".", 1)[-1]
        held_res = set().union(*(h[1] for h in held))
        if not held_res:
            return
        strict = [r for r in held_res if r[0] in _STRICT_KINDS]
        qual = f"{fi.summary['qual']}"
        if strict:
            for cls in _classify_await(flow, fi, callee):
                key = (fi.path, qual, callee, cls)
                if key in seen:
                    continue
                seen.add(key)
                res = strict[0]
                yield Finding(
                    "DTPU008",
                    fi.path,
                    ev["line"],
                    f"{_describe(res)} held across {cls} "
                    f"(await {final}) [in {qual}]",
                )
        # same-pool re-acquisition: checked for EVERY held pool token,
        # strict or not — this is the PR 7 deadlock shape
        held_pools = {r[1] for r in held_res if r[0] == "pool"}
        if held_pools:
            re_acq = _await_pool_tokens(flow, fi, callee) & held_pools
            for tok in sorted(re_acq):
                key = (fi.path, qual, callee, "pool", tok)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    "DTPU008",
                    fi.path,
                    ev["line"],
                    f"re-acquisition from pool {tok.split('::')[-1]} while "
                    f"holding one of its connections (await {final}) — the "
                    f"PR 7 claim-pool deadlock shape [in {qual}]",
                )


def _describe(res) -> str:
    kind = res[0]
    if kind == "tx":
        return "DB transaction (single-writer lock / pooled connection)"
    if kind == "bucket":
        return "QoS token-bucket charge"
    if kind == "slot":
        return "engine slot"
    return f"{kind} {res[1]}"
