"""DTPU002: host↔device syncs/transfers in serve/ops hot paths.

The serve engine's decode loop runs per generated token; one stray
``.item()`` (a blocking device→host round trip) or a re-uploaded host
list (``jnp.asarray`` per token) caps throughput at the host-device
link instead of the TPU — and no unit test notices, because parity
tests don't measure dispatch counts. Flagged inside
``dstack_tpu/serve/engine.py``, ``dstack_tpu/serve/openai_server.py``,
and ``dstack_tpu/ops/``:

anywhere in the file (these block even in dispatch code, and cannot
appear inside traced code at all):

- ``.item()`` — blocking scalar pull
- ``jax.device_get(...)`` / ``from jax import device_get``
- ``.block_until_ready()``
- ``np.asarray(...)`` (numpy) — materializes a device array on host

only inside *class method* bodies — the engine's dispatch code. The
module-level functions in these files are jit-traced model code where
``jnp.asarray`` is a free constant fold, so flagging them would be
pure noise:

- ``jnp.asarray/jnp.array/jnp.arange(...)`` — a fresh host→device
  upload per call; per-token call sites should mirror device-resident
  state instead (see ``InferenceEngine._decode_state``)
- ``float(x[...])`` / ``int(x[...])`` — scalar coercion of an indexed
  array forces a device sync when ``x`` is device-resident
- ``print(...)`` with a non-literal argument — formatting a device
  array blocks on its transfer

Findings name the enclosing function so the baseline shrinks method
by method as call sites get fixed. Most grandfathered sites are
per-request (prefill/activation) rather than per-token — acceptable
today, still worth burning down.
"""

import ast

from tools.dtpu_lint.core import FileRule, Finding, register

_UPLOAD_FUNCS = {"asarray", "array", "arange", "zeros", "ones", "full"}


def _collect_aliases(tree: ast.AST) -> dict:
    """name → one of {"numpy", "jax.numpy", "jax"} plus bare names
    bound to jax.device_get, from the file's imports."""
    aliases: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases[a.asname or "numpy"] = "numpy"
                elif a.name == "jax.numpy":
                    aliases[a.asname or "jax"] = (
                        "jax.numpy" if a.asname else "jax"
                    )
                elif a.name == "jax":
                    aliases[a.asname or "jax"] = "jax"
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        aliases[a.asname or "numpy"] = "jax.numpy"
                    elif a.name == "device_get":
                        aliases[a.asname or "device_get"] = "jax.device_get"
            elif node.module == "numpy":
                for a in node.names:
                    if a.name == "asarray":
                        aliases[a.asname or "asarray"] = "numpy.asarray"
    return aliases


def _receiver_root(node: ast.AST):
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _Checker(ast.NodeVisitor):
    def __init__(self, aliases: dict, relpath: str):
        self.aliases = aliases
        self.relpath = relpath
        self.findings: list = []
        self._ctx: list = []  # enclosing function names
        self._method_depth = 0  # >0 while inside a class-method body

    # -- context tracking ---------------------------------------------------

    def visit_ClassDef(self, node):
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # constructors run once per engine, not per token/
                # request: a one-time jnp.zeros there is allocation,
                # not a dispatch-path upload (the blocking-sync checks
                # above still apply anywhere in the file)
                dispatch = stmt.name not in (
                    "__init__", "__post_init__", "__new__"
                )
                self._ctx.append(f"{node.name}.{stmt.name}")
                self._method_depth += 1 if dispatch else 0
                for inner in stmt.body:
                    self.visit(inner)
                self._method_depth -= 1 if dispatch else 0
                self._ctx.pop()
            else:
                self.visit(stmt)

    def _visit_fn(self, node):
        self._ctx.append(node.name)
        for stmt in node.body:
            self.visit(stmt)
        self._ctx.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- checks -------------------------------------------------------------

    def _where(self) -> str:
        return self._ctx[-1] if self._ctx else "<module>"

    def _emit(self, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding("DTPU002", self.relpath, node.lineno, f"{msg} [in {self._where()}]")
        )

    def visit_Call(self, node: ast.Call):
        func = node.func
        # .item() / .block_until_ready(): blocking pulls, any context
        if isinstance(func, ast.Attribute) and not node.args and not node.keywords:
            if func.attr == "item":
                self._emit(node, "host sync: .item() blocks on a device→host transfer")
            elif func.attr == "block_until_ready":
                self._emit(node, "host sync: .block_until_ready()")
        # module-qualified calls
        if isinstance(func, ast.Attribute):
            root = _receiver_root(func)
            mod = self.aliases.get(root) if root is not None else None
            # fully-qualified jax.numpy.<fn>: the root alias resolves
            # to "jax", so treat a `.numpy` receiver as the jnp module
            if (
                mod == "jax"
                and isinstance(func.value, ast.Attribute)
                and func.value.attr == "numpy"
            ):
                mod = "jax.numpy"
            if mod == "jax" and func.attr == "device_get":
                self._emit(node, "host sync: jax.device_get() pulls arrays to host")
            elif mod == "numpy" and func.attr == "asarray":
                self._emit(
                    node,
                    "host copy: np.asarray() materializes a (possibly "
                    "device) array on host",
                )
            elif (
                mod == "jax.numpy"
                and func.attr in _UPLOAD_FUNCS
                and self._method_depth > 0
            ):
                self._emit(
                    node,
                    f"per-call device upload: jnp.{func.attr}() in engine "
                    "dispatch code (hoist, or mirror device-resident state)",
                )
        elif isinstance(func, ast.Name):
            bound = self.aliases.get(func.id)
            if bound == "jax.device_get":
                self._emit(node, "host sync: jax.device_get() pulls arrays to host")
            elif bound == "numpy.asarray":
                self._emit(
                    node,
                    "host copy: np.asarray() materializes a (possibly "
                    "device) array on host",
                )
            elif self._method_depth > 0:
                if func.id in ("float", "int") and len(node.args) == 1 and isinstance(
                    node.args[0], ast.Subscript
                ):
                    self._emit(
                        node,
                        f"host sync: {func.id}() on an indexed array forces "
                        "a device→host transfer",
                    )
                elif func.id == "print" and any(
                    not isinstance(a, ast.Constant) for a in node.args
                ):
                    self._emit(
                        node,
                        "print() of a non-literal in dispatch code blocks "
                        "if the value is a device array",
                    )
        self.generic_visit(node)


@register
class HostSyncRule(FileRule):
    id = "DTPU002"
    name = "host-device sync/transfer in serve/ops hot paths"
    scope = (
        "dstack_tpu/serve/engine.py",
        "dstack_tpu/serve/openai_server.py",
        "dstack_tpu/ops/*.py",
    )

    def check(self, tree, src, relpath, repo):
        checker = _Checker(_collect_aliases(tree), relpath)
        checker.visit(tree)
        return checker.findings
