"""Rule modules — importing this package registers every rule."""

from tools.dtpu_lint.rules import (  # noqa: F401
    async_blocking,
    host_sync,
    metric_hygiene,
    recompile,
    retry_after,
    settings_drift,
    silent_except,
)
