"""Rule modules — importing this package registers every rule."""

from tools.dtpu_lint.rules import (  # noqa: F401
    async_blocking,
    cancel_safety,
    fault_coverage,
    host_sync,
    lock_discipline,
    metric_hygiene,
    recompile,
    resource_await,
    retry_after,
    settings_drift,
    silent_except,
    spmd,
)
