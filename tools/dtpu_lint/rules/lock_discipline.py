"""DTPU009: entity-lock / advisory-lock discipline.

The server's locks are namespaced (``jobs``, ``runs``, ``instances``,
``volumes``, ``gateways``, placement …) and come in two flavors:
non-blocking SKIP-LOCKED claims (``claim_one`` / ``claim_batch``) and
blocking waits (``lock_ctx`` → ``LockSet.acquire``). Three shapes are
deadlock-prone and invisible to per-file review:

- **nested acquisition of the same namespace** — a handler that claims
  ``jobs`` and awaits a helper that claims ``jobs`` again waits on (or
  skips past) its own claim, depending on engine; either is a bug;
- **inconsistent acquisition order across functions** — function A
  takes ``jobs`` then ``instances`` while function B takes
  ``instances`` then ``jobs``: run concurrently they ABBA-deadlock.
  The order graph is global, so only a project-wide pass can see it;
- **awaiting a blocking cross-namespace lock while one is held** —
  a blocking wait of unbounded depth under a held claim pins the claim
  (and on Postgres its lock-pool connection) behind another queue.

Acquisitions are tracked interprocedurally: holding ``jobs`` and
awaiting a function that three calls down claims ``instances`` records
the ``jobs → instances`` edge. Namespaces are recognized from the
first string-literal argument; dynamically-named locks participate in
held-state tracking but not in order analysis.
"""

from typing import Iterable

from tools.dtpu_lint.core import Finding, ProjectRule, register
from tools.dtpu_lint.flow import (
    BLOCKING_LOCK_NAMES,
    CLAIM_NAMES,
    get_flow,
    report_paths,
)


@register
class LockDisciplineRule(ProjectRule):
    id = "DTPU009"
    name = "lock-order / nested-lock discipline"

    def check_project(self, repo) -> Iterable[Finding]:
        flow = get_flow(repo)
        scope = report_paths(repo)
        findings: list = []
        # (ns_before, ns_after) -> [(path, qual, line)]
        edges: dict = {}
        for fi in flow.functions():
            if fi.path not in scope or not fi.summary["is_async"]:
                continue
            self._walk(flow, fi, findings, edges)
        # order-graph conflicts: X→Y and Y→X both witnessed
        reported = set()
        for (x, y), wits in sorted(edges.items()):
            if (y, x) not in edges or x >= y:
                continue
            other = edges[(y, x)]
            for path, qual, line in wits:
                key = (path, qual, x, y)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(
                    Finding(
                        "DTPU009",
                        path,
                        line,
                        f"inconsistent lock order: {x} acquired before {y} "
                        f"[in {qual}], but {y} before {x} "
                        f"[in {other[0][1]}] — concurrent ABBA deadlock",
                    )
                )
            for path, qual, line in other:
                key = (path, qual, y, x)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(
                    Finding(
                        "DTPU009",
                        path,
                        line,
                        f"inconsistent lock order: {y} acquired before {x} "
                        f"[in {qual}], but {x} before {y} "
                        f"[in {wits[0][1]}] — concurrent ABBA deadlock",
                    )
                )
        return findings

    def _walk(self, flow, fi, findings, edges) -> None:
        f = fi.summary
        qual = f["qual"]
        held: list = []  # (ns-or-None, callee)
        seen = set()
        for ev in f["events"]:
            k = ev["k"]
            callee = ev.get("callee")
            if k == "exit":
                if held and held[-1][1] == callee:
                    held.pop()
                continue
            if k not in ("enter", "await") or not callee:
                continue
            final = callee.rsplit(".", 1)[-1]
            noqa = set(ev.get("noqa", ()))
            is_claim = final in CLAIM_NAMES
            is_blocking = final in BLOCKING_LOCK_NAMES
            if (is_claim or is_blocking) and "DTPU009" not in noqa:
                ns = ev.get("arg0")
                self._check_acquire(
                    fi, qual, ev, ns, is_blocking, held, findings, edges,
                    seen, via=None,
                )
                if k == "enter":
                    held.append((ns, callee))
                continue
            if k == "enter":
                held.append((None, callee))  # non-lock ctx: neutral
                continue
            # plain await: does the callee transitively acquire locks?
            if not held or all(h[0] is None for h in held):
                continue
            if "DTPU009" in noqa:
                continue
            reach = set()
            for t in flow.callee_facts(fi, callee):
                reach |= set(t.lock_reach)
            for ns2, blocking2 in sorted(
                reach, key=lambda e: (str(e[0]), e[1])
            ):
                self._check_acquire(
                    fi, qual, ev, ns2, blocking2, held, findings, edges,
                    seen, via=callee,
                )

    def _check_acquire(
        self, fi, qual, ev, ns, blocking, held, findings, edges, seen, via
    ) -> None:
        suffix = f" via {via}" if via else ""
        for hns, _ in held:
            if hns is None:
                continue
            if ns is not None and ns == hns:
                key = ("nested", ns, via)
                if key not in seen:
                    seen.add(key)
                    findings.append(
                        Finding(
                            "DTPU009",
                            fi.path,
                            ev["line"],
                            f"nested acquisition of lock namespace "
                            f"'{ns}'{suffix} while already holding it "
                            f"[in {qual}]",
                        )
                    )
                continue
            if ns is not None:
                edges.setdefault((hns, ns), []).append(
                    (fi.path, qual, ev["line"])
                )
            if blocking:
                key = ("blocking", hns, ns, via)
                if key not in seen:
                    seen.add(key)
                    findings.append(
                        Finding(
                            "DTPU009",
                            fi.path,
                            ev["line"],
                            f"blocking acquisition of lock namespace "
                            f"'{ns or '<dynamic>'}'{suffix} while holding "
                            f"'{hns}' — unbounded wait under a held lock "
                            f"[in {qual}]",
                        )
                    )
