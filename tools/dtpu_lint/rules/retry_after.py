"""DTPU007: backpressure contract — 429/503 responses carry Retry-After.

Every overload answer in the system tells the client *when to come
back*: the routing plane's pool-exhausted 503 derives its hint from the
earliest breaker half-open (PR 3), the QoS edges' 429s from the token
bucket's refill schedule. A 429/503 without ``Retry-After`` invites the
worst client behavior — immediate blind retry — exactly when the system
is shedding load to survive. PR 3 and PR 5 established the invariant by
convention; this rule enforces it.

Flags any ``web.json_response(...)`` / ``web.Response(...)`` /
``web.StreamResponse(...)`` constructed with ``status=429`` or
``status=503`` whose ``headers`` argument is missing, or is a dict
literal without a ``"Retry-After"`` key. A non-literal ``headers``
expression is accepted (the rule cannot prove its contents; reviewers
can). Handlers with a genuine reason to omit the header take a
``# dtpu: noqa[DTPU007] <why>`` pragma.
"""

import ast
from typing import Iterable, Optional

from tools.dtpu_lint.core import FileRule, Finding, register

_RESPONSE_CTORS = {"json_response", "Response", "StreamResponse"}
_BACKPRESSURE_STATUSES = {429, 503}


def _status_of(call: ast.Call) -> Optional[int]:
    for kw in call.keywords:
        if kw.arg == "status" and isinstance(kw.value, ast.Constant):
            v = kw.value.value
            if isinstance(v, int):
                return v
    return None


def _headers_have_retry_after(call: ast.Call) -> Optional[bool]:
    """True/False when provable from a literal ``headers=`` dict;
    None when headers is a non-literal expression (benefit of the
    doubt) — a missing ``headers`` kwarg returns False."""
    for kw in call.keywords:
        if kw.arg != "headers":
            continue
        if isinstance(kw.value, ast.Dict):
            return any(
                isinstance(k, ast.Constant) and k.value == "Retry-After"
                for k in kw.value.keys
            )
        return None  # built elsewhere: cannot prove, accept
    return False


def check_retry_after(src: str, relpath: str = "<string>") -> list:
    tree = ast.parse(src, filename=relpath)
    findings: list = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _RESPONSE_CTORS
        ):
            continue
        status = _status_of(node)
        if status not in _BACKPRESSURE_STATUSES:
            continue
        if _headers_have_retry_after(node) is False:
            findings.append(
                Finding(
                    "DTPU007",
                    relpath,
                    node.lineno,
                    f"{status} response without a Retry-After header: "
                    "overload answers must tell clients when to come "
                    "back (pool.retry_after_hint() / the QoS bucket's "
                    "refill hint)",
                )
            )
    return findings


@register
class RetryAfterRule(FileRule):
    id = "DTPU007"
    name = "backpressure contract (429/503 ⇒ Retry-After)"
    scope = ("dstack_tpu/**/*.py",)

    def check(self, tree, src, relpath, repo) -> Iterable[Finding]:
        return check_retry_after(src, relpath)
