"""DTPU012-014: SPMD sharding discipline for the multi-host serve surface.

ROADMAP item 1 promotes ``parallel/`` and the tp2 dryrun into
multi-host serving, where the failure modes are categorically worse
than single-host: a typo'd mesh-axis name fails at trace time on the
fleet (the most expensive place to find it), and a collective that
only *some* members execute — because a host-side Python branch
diverged, or because a host sync forced per-host values — is not a
crash but a fleet-wide deadlock: every other member blocks in the
collective waiting for the missing participant until the job is
killed from outside. These rules make those shapes fail in tier-1 CI
on CPU instead (flow.py's SPMD index; see also tools/shardcheck for
the dynamic abstract-trace gate):

- **DTPU012** sharding discipline — axis names passed to collectives
  (``psum``/``all_gather``/``ppermute``/``axis_index``/``pmean``/...),
  ``shard_map`` ``in_specs``/``out_specs``/``axis_names``, and
  ``PartitionSpec`` literals must resolve to string literals drawn
  from the mesh-axis vocabulary declared in ``parallel/mesh.py``
  (``AXES``). The library idiom threads axis names through parameters
  (``axis_name: str = "sp"``) and closures, so resolution follows the
  interprocedural binding fixpoint in :class:`flow.SpmdFlow` — a
  literal is checked wherever it *enters* the flow (the call site
  passing ``axis_name="typo"`` gets the finding, not the collective
  ten frames below).
- **DTPU013** SPMD purity — no host syncs (``.item()``,
  ``jax.device_get``, ``np.asarray``, ``.block_until_ready()``), no
  host callbacks (``pure_callback``/``io_callback``/
  ``jax.debug.callback``) anywhere in shard_map-reachable code, and no
  Python branching on traced per-shard values inside ``shard_map``
  bodies (body parameters are per-shard arrays by construction; a
  branch on one diverges per member).
- **DTPU014** collective discipline — every collective reachable from
  a ``shard_map`` body must execute unconditionally on all members
  (no collective under a Python ``if``/``while``/early-``return`` on
  per-shard data, interprocedurally), and every axis a body's
  collectives use must appear in that ``shard_map``'s specs or
  ``axis_names`` (an unbound axis is a trace-time NameError on the
  fleet).

Opt-outs: ``# dtpu: noqa[DTPU01x] reason`` on the offending line (or
the comment/decorator block above it), same contract as every rule.
"""

from pathlib import Path

from tools.dtpu_lint.core import Finding, ProjectRule, register
from tools.dtpu_lint.flow import SPMD_GLOBS, get_spmd_flow


def _vocab_str(vocab) -> str:
    return "{" + ", ".join(sorted(vocab)) + "}"


class _SpmdRuleBase(ProjectRule):
    #: participates in --changed-only runs when a changed file matches
    scope = SPMD_GLOBS

    def _flow(self, repo: Path):
        return get_spmd_flow(Path(repo))


@register
class SpmdShardingRule(_SpmdRuleBase):
    id = "DTPU012"
    name = "mesh-axis names must be literals from parallel/mesh.py AXES"

    def check_project(self, repo):
        flow = self._flow(repo)
        vocab = flow.vocab
        if not vocab:
            return []  # no declared vocabulary to check against
        out: set = set()

        def emit(path, line, msg):
            out.add(Finding(self.id, path, line, msg))

        def check_ref(path, line, ref, what, noqa):
            if "DTPU012" in noqa:
                return
            if ref["t"] == "none":
                return
            if ref["t"] == "lit":
                if ref["v"] not in vocab:
                    emit(
                        path, line,
                        f"{what}: axis '{ref['v']}' is not a declared mesh "
                        f"axis {_vocab_str(vocab)} (parallel/mesh.py AXES)",
                    )
                return
            if ref["t"] == "param":
                binds = flow.resolve_axis(path, ref)
                if binds is None:
                    emit(
                        path, line,
                        f"{what}: axis flows through param "
                        f"'{ref['p']}' of {ref['fq']} with no string "
                        "default and no literal call site — not "
                        "statically resolvable to a mesh axis",
                    )
                    return
                for lit, (opath, oline) in sorted(binds.items()):
                    if lit not in vocab:
                        emit(
                            opath, oline or line,
                            f"axis '{lit}' bound to param '{ref['p']}' of "
                            f"{ref['fq']} is not a declared mesh axis "
                            f"{_vocab_str(vocab)} (parallel/mesh.py AXES)",
                        )
                return
            emit(
                path, line,
                f"{what}: axis is not a static string "
                f"(got `{ref.get('v', '?')}`)",
            )

        for key, f in flow.functions_items():
            path = flow.paths[key]
            for ev in f["collectives"]:
                check_ref(
                    path, ev["line"], ev["axis"],
                    f"collective {ev['fn']}() in {f['name']}",
                    set(ev.get("noqa", ())),
                )
            for sm in f["shard_maps"]:
                noqa = set(sm.get("noqa", ()))
                if sm["unknown_specs"] and "DTPU012" not in noqa:
                    emit(
                        path, sm["line"],
                        f"shard_map in {f['name']}: in_specs/out_specs not "
                        "statically resolvable to PartitionSpec literals",
                    )
                for ref in (*sm["in_axes"], *sm["out_axes"], *sm["axis_names"]):
                    check_ref(
                        path, sm["line"], ref,
                        f"shard_map spec in {f['name']}", noqa,
                    )
            for ps in f["pspecs"]:
                noqa = set(ps.get("noqa", ()))
                for ref in ps["axes"]:
                    # bare PartitionSpec constructions are literal-checked
                    # only: dynamic spec builders (sharding.py's
                    # logical→mesh translation) are legitimate
                    if ref["t"] == "lit":
                        check_ref(
                            path, ps["line"], ref,
                            f"PartitionSpec in {f['name']}", noqa,
                        )
        return sorted(out, key=lambda f: (f.path, f.line, f.message))


@register
class SpmdPurityRule(_SpmdRuleBase):
    id = "DTPU013"
    name = "no host syncs/callbacks/per-shard branches in SPMD-traced code"

    def check_project(self, repo):
        flow = self._flow(repo)
        out: list = []
        for key in sorted(flow.traced):
            f = flow.funcs[key]
            path = flow.paths[key]
            for ev in f["host_syncs"]:
                if "DTPU013" in set(ev.get("noqa", ())):
                    continue
                out.append(
                    Finding(
                        self.id, path, ev["line"],
                        f"host sync {ev['what']} in SPMD-traced code "
                        f"[in {f['name']}] — on multi-host this forces a "
                        "per-host value where members must agree "
                        "(deadlock around the next collective)",
                    )
                )
        for key in sorted(flow.bodies):
            f = flow.funcs[key]
            path = flow.paths[key]
            for ev in f["tainted_branches"]:
                if "DTPU013" in set(ev.get("noqa", ())):
                    continue
                out.append(
                    Finding(
                        self.id, path, ev["line"],
                        f"Python branch on per-shard value "
                        f"`{ev['test']}` inside shard_map body "
                        f"[in {f['name']}] — use lax.cond/jnp.where; a "
                        "host branch diverges per member",
                    )
                )
        return sorted(out, key=lambda f: (f.path, f.line, f.message))


@register
class SpmdCollectiveRule(_SpmdRuleBase):
    id = "DTPU014"
    name = "collectives unconditional + axes covered by shard_map specs"

    def check_project(self, repo):
        flow = self._flow(repo)
        out: set = set()
        for key in sorted(flow.traced):
            f = flow.funcs[key]
            path = flow.paths[key]
            for ev in f["collectives"]:
                if not ev.get("cond"):
                    continue
                if "DTPU014" in set(ev.get("noqa", ())):
                    continue
                out.add(
                    Finding(
                        self.id, path, ev["line"],
                        f"collective {ev['fn']}() under data-dependent "
                        f"Python control flow [in {f['name']}] — members "
                        "that skip it leave the rest of the fleet blocked "
                        "in the collective (use lax.cond so every member "
                        "traces both paths)",
                    )
                )
        # axis coverage: body's transitive collective axes ⊆ site specs
        for wkey, sm, body_keys in flow.body_sites:
            if not body_keys:
                continue
            noqa = set(sm.get("noqa", ()))
            if "DTPU014" in noqa or sm["unknown_specs"]:
                continue
            path = flow.paths[wkey]
            spec_lits: set = set()
            resolvable = True
            for ref in (*sm["in_axes"], *sm["out_axes"], *sm["axis_names"]):
                binds = flow.resolve_axis(path, ref)
                if binds is None:
                    resolvable = False  # DTPU012's finding, not ours
                    continue
                spec_lits.update(binds)
            if not resolvable:
                continue
            for body_key in body_keys:
                bname = flow.funcs[body_key]["name"]
                for okey, ev in flow.transitive_collective_axes(body_key):
                    if "DTPU014" in set(ev.get("noqa", ())):
                        continue
                    binds = flow.resolve_axis(flow.paths[okey], ev["axis"])
                    if binds is None:
                        continue
                    for lit in sorted(binds):
                        if lit not in spec_lits:
                            out.add(
                                Finding(
                                    self.id, path, sm["line"],
                                    f"shard_map body '{bname}' runs "
                                    f"{ev['fn']}() over axis '{lit}' which "
                                    "appears in neither in_specs/out_specs "
                                    "nor axis_names — unbound axis at "
                                    "trace time on the fleet",
                                )
                            )
        return sorted(out, key=lambda f: (f.path, f.line, f.message))
