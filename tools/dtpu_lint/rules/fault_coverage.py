"""DTPU011: fault-point boundary coverage for raw I/O.

The deterministic fault layer (:mod:`dstack_tpu.faults`) can only
exercise failure paths that sit behind a ``faults.fire`` point, and
the chaos suite can only assert invariants about errors that arrive
TYPED. PR 5's worst find was the gap between the two: ``aiohttp``
raised a raw ``OSError`` below the agent transport whose handlers
mapped ``ClientConnectionError``/timeouts only — the reconciler tick
crashed on an exception class nobody had seen in a test, because no
injection point could produce it there.

This rule generalizes that incident. For every raw network/DB I/O
call site in the instrumented planes (``aiohttp`` session calls,
``asyncio.open_connection``, asyncpg ``conn.fetch*``):

- **uninstrumented I/O**: the call is not under any fault injection
  point — neither its function nor (transitively) every caller path
  fires one — so no chaos plan can fail it deterministically;
- **unmapped OSError** (the PR 5 shape): the call sits in a ``try``
  whose handlers name specific transport errors but nothing covering
  ``OSError`` — the one class raw sockets add beneath every HTTP
  client — so a tunnel reset/DNS failure escapes the typed-error
  boundary exactly like the original bug.

Sites below the fault boundary by design (wire-protocol internals,
startup-only paths that run before the chaos planes are live) opt out
with ``# dtpu: noqa[DTPU011] <why>``.
"""

from typing import Iterable

from tools.dtpu_lint.core import Finding, ProjectRule, register
from tools.dtpu_lint.flow import (
    _is_db_io,
    _is_net_io,
    get_flow,
    report_paths,
)

#: handler type names (finals) that cover a raw OSError
_OS_COVERING = frozenset({"OSError", "IOError", "Exception", "BaseException"})


@register
class FaultBoundaryCoverageRule(ProjectRule):
    id = "DTPU011"
    name = "raw I/O outside fault-point / typed-error boundary"

    def check_project(self, repo) -> Iterable[Finding]:
        flow = get_flow(repo)
        scope = report_paths(repo)
        for fi in flow.functions():
            if fi.path not in scope:
                continue
            f = fi.summary
            qual = f["qual"]
            seen = set()
            for ev in f["events"]:
                if ev["k"] not in ("await", "call", "enter"):
                    continue
                callee = ev.get("callee")
                if not callee:
                    continue
                net = _is_net_io(callee)
                db = _is_db_io(callee)
                if not (net or db):
                    continue
                kind = "network" if net else "DB"
                if not fi.covered:
                    key = ("fire", callee)
                    if key not in seen:
                        seen.add(key)
                        yield Finding(
                            "DTPU011",
                            fi.path,
                            ev["line"],
                            f"{kind} I/O ({callee}) not under any fault "
                            f"injection point — no chaos plan can fail it "
                            f"deterministically [in {qual}]",
                        )
                handlers = ev.get("handlers") or []
                if handlers:
                    finals = {h.rsplit(".", 1)[-1] for h in handlers}
                    if not finals & _OS_COVERING:
                        key = ("os", callee)
                        if key not in seen:
                            seen.add(key)
                            yield Finding(
                                "DTPU011",
                                fi.path,
                                ev["line"],
                                f"{kind} I/O ({callee}) inside a try that "
                                f"maps {sorted(finals)} but not OSError — "
                                f"a raw socket error escapes the typed-"
                                f"error boundary (the PR 5 unmapped "
                                f"transport error) [in {qual}]",
                            )
