"""Framework core: rule registry, pragmas, baseline, runner.

Pieces, in dependency order:

- :class:`Finding` — one diagnostic, keyed for baseline matching by
  ``(rule, path, message)`` (line numbers drift with every edit; the
  message text is stable per call site because rules interpolate the
  offending symbol, not the position).
- :class:`FileRule` / :class:`ProjectRule` — a file rule sees one
  parsed AST at a time and declares the path globs it applies to; a
  project rule runs once per lint over the whole repo (docs-coverage
  style checks that aren't per-file).
- ``# dtpu: noqa[RULE]`` pragmas — line-scoped opt-outs, rule id
  required so an unrelated rule never hides behind someone else's
  exemption. A reason after the bracket is conventional (reviewers
  enforce it; the PR that adds a bare one gets asked why).
- Baseline — grandfathered findings checked into
  ``tools/dtpu_lint/baseline.json`` so a new rule can land with the
  gate green while the backlog shrinks PR by PR. Shrink-only: the
  gate fails on findings beyond the baseline AND on stale entries
  (fixed findings must leave the file, or they'd mask regressions).
"""

from __future__ import annotations

import ast
import json
import re
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional, Sequence

REPO = Path(__file__).resolve().parent.parent.parent

# default lint surface: the shipped package (tests and tools lint
# themselves a rule at a time via fixtures, not the repo gate)
DEFAULT_GLOBS = ("dstack_tpu/**/*.py",)

BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

_PRAGMA_RE = re.compile(
    r"#\s*dtpu:\s*noqa\[(?P<rules>[A-Za-z0-9_,\s]+)\](?P<reason>[^\n]*)"
)


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``key`` ignores the line so baselines survive
    unrelated edits above the call site."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    @property
    def key(self) -> tuple:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


def glob_match(relpath: str, pattern: str) -> bool:
    """Pathlib-style glob matching where ``**/`` spans zero or more
    directories — plain :func:`fnmatch.fnmatch` gives ``**`` no
    special meaning, so ``pkg/**/*.py`` would silently exclude
    top-level ``pkg/x.py`` while ``Path.glob`` includes it."""
    out = []
    i = 0
    while i < len(pattern):
        if pattern.startswith("**/", i):
            out.append(r"(?:[^/]+/)*")
            i += 3
        elif pattern[i] == "*":
            out.append(r"[^/]*")
            i += 1
        else:
            out.append(re.escape(pattern[i]))
            i += 1
    return re.fullmatch("".join(out), relpath) is not None


class FileRule:
    """Base for per-file AST rules. Subclasses set ``id``/``name``/
    ``scope`` and implement :meth:`check`."""

    id: str = ""
    name: str = ""
    #: ``**``-aware globs over repo-relative posix paths
    scope: tuple = ("dstack_tpu/**/*.py",)

    def applies(self, relpath: str) -> bool:
        return any(glob_match(relpath, g) for g in self.scope)

    def check(
        self, tree: ast.AST, src: str, relpath: str, repo: Path
    ) -> Iterable[Finding]:
        raise NotImplementedError


class ProjectRule:
    """Base for once-per-lint whole-repo rules (docs coverage etc.)."""

    id: str = ""
    name: str = ""

    def check_project(self, repo: Path) -> Iterable[Finding]:
        raise NotImplementedError


RULES: dict = {}


def register(cls):
    """Class decorator: instantiate and index the rule by id."""
    rule = cls()
    if not rule.id:
        raise ValueError(f"{cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return cls


def all_rules() -> dict:
    """id → rule instance, importing the rule modules on first use."""
    import tools.dtpu_lint.rules  # noqa: F401 - registration side effect

    return RULES


def _pragma_rules(line: str) -> Optional[set]:
    """Rule ids a source line opts out of, or None without a pragma."""
    m = _PRAGMA_RE.search(line)
    if m is None:
        return None
    return {r.strip().upper() for r in m.group("rules").split(",") if r.strip()}


def pragma_lines(lines: Sequence[str], lineno: int) -> Iterable[str]:
    """The lines a pragma for a finding at ``lineno`` may live on: the
    line itself, then the contiguous block of comment-only and
    decorator lines directly above it — multi-line reasons and
    ``@decorated`` defs both keep their pragma adjacent to the code it
    excuses."""
    if not (1 <= lineno <= len(lines)):
        return
    yield lines[lineno - 1]
    ln = lineno - 1
    while ln >= 1:
        stripped = lines[ln - 1].lstrip()
        if not stripped.startswith(("#", "@")):
            break
        yield lines[ln - 1]
        ln -= 1


def suppressed(finding: Finding, lines: Sequence[str]) -> bool:
    """True when the finding's line — or the contiguous comment/
    decorator block directly above it (the readable spot for a long
    reason) — carries a matching noqa pragma."""
    for line in pragma_lines(lines, finding.line):
        rules = _pragma_rules(line)
        if rules is not None and finding.rule.upper() in rules:
            return True
    return False


def check_file_source(
    src: str,
    relpath: str = "<string>",
    rule_ids: Optional[Sequence[str]] = None,
    repo: Optional[Path] = None,
) -> list:
    """Run file rules over one source string → sorted Findings (pragma
    suppression applied). The unit-test / shim entry point."""
    repo = repo or REPO
    rules = all_rules()
    picked = [
        r
        for rid, r in sorted(rules.items())
        if isinstance(r, FileRule)
        and (rule_ids is None or rid in rule_ids)
    ]
    tree = ast.parse(src, filename=relpath)
    lines = src.splitlines()
    out: list = []
    for rule in picked:
        if rule_ids is None and not rule.applies(relpath):
            continue
        for f in rule.check(tree, src, relpath, repo):
            if not suppressed(f, lines):
                out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule, f.message))


def iter_lint_files(
    repo: Path, paths: Optional[Sequence[str]] = None
) -> list:
    """Repo-relative posix paths to lint (sorted, deduped)."""
    rels: set = set()
    if paths:
        for p in paths:
            pp = Path(p)
            if not pp.is_absolute():
                pp = repo / pp
            pp = pp.resolve()
            try:
                rel = pp.relative_to(repo)
            except ValueError:
                raise ValueError(
                    f"path outside the repo ({repo}): {p}"
                ) from None
            if pp.is_dir():
                rels.update(
                    (rel / f.relative_to(pp)).as_posix()
                    for f in pp.rglob("*.py")
                )
            else:
                rels.add(rel.as_posix())
    else:
        for g in DEFAULT_GLOBS:
            rels.update(f.relative_to(repo).as_posix() for f in repo.glob(g))
    return sorted(rels)


def run_lint(
    repo: Optional[Path] = None,
    paths: Optional[Sequence[str]] = None,
    rule_ids: Optional[Sequence[str]] = None,
    project_rules: bool = True,
) -> list:
    """Lint the repo (or ``paths``) → sorted Findings, pragmas applied,
    baseline NOT applied (callers compare via :func:`apply_baseline`)."""
    repo = repo or REPO
    rules = all_rules()
    file_rules = [
        r
        for rid, r in sorted(rules.items())
        if isinstance(r, FileRule) and (rule_ids is None or rid in rule_ids)
    ]
    findings: list = []
    for rel in iter_lint_files(repo, paths):
        f = repo / rel
        applicable = [r for r in file_rules if r.applies(rel)]
        if not applicable:
            continue
        try:
            src = f.read_text()
            tree = ast.parse(src, filename=str(f))
        except (OSError, SyntaxError) as e:
            findings.append(
                Finding("DTPU000", rel, 1, f"unparseable file: {e}")
            )
            continue
        lines = src.splitlines()
        for rule in applicable:
            for finding in rule.check(tree, src, rel, repo):
                if not suppressed(finding, lines):
                    findings.append(finding)
    if project_rules:
        # project-rule findings honor line pragmas too: flow rules
        # (DTPU008-011) point at real source lines where a
        # `# dtpu: noqa[RULE] reason` is the sanctioned opt-out
        line_cache: dict = {}

        def _lines_for(rel: str):
            if rel not in line_cache:
                try:
                    line_cache[rel] = (repo / rel).read_text().splitlines()
                except OSError:
                    line_cache[rel] = []
            return line_cache[rel]

        # path-restricted runs (--changed-only, explicit paths) include
        # only project rules that declare a `scope`, and only when a
        # scanned path matches it; their findings are then filtered to
        # the scanned set so an unrelated file's finding can't fail a
        # pre-commit pass. Scope-less project rules (repo-wide
        # docs-coverage style) still run on full lints only.
        scanned = set(iter_lint_files(repo, paths)) if paths else None
        for rid, r in sorted(rules.items()):
            # a project rule shipped as a sub-id of a file rule
            # (DTPU004-DOCS) runs whenever its base id is selected
            if isinstance(r, ProjectRule) and (
                rule_ids is None
                or rid in rule_ids
                or rid.split("-")[0] in rule_ids
            ):
                if scanned is not None:
                    scope = getattr(r, "scope", None)
                    if not scope or not any(
                        glob_match(rel, g) for rel in scanned for g in scope
                    ):
                        continue
                for finding in r.check_project(repo):
                    if scanned is not None and finding.path not in scanned:
                        continue
                    if not suppressed(finding, _lines_for(finding.path)):
                        findings.append(finding)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


@dataclass
class BaselineDiff:
    """New findings beyond the baseline + stale (over-granted) entries."""

    new: list = field(default_factory=list)
    stale: list = field(default_factory=list)  # [(key, granted, seen)]

    @property
    def clean(self) -> bool:
        return not self.new and not self.stale


def load_baseline(path: Optional[Path] = None) -> Counter:
    """key → grandfathered count (empty when the file is absent)."""
    path = path or BASELINE_PATH
    if not Path(path).exists():
        return Counter()
    data = json.loads(Path(path).read_text())
    out: Counter = Counter()
    for e in data.get("entries", []):
        out[(e["rule"], e["path"], e["message"])] += int(e.get("count", 1))
    return out


def write_baseline(findings: Iterable[Finding], path: Optional[Path] = None) -> dict:
    """Persist current findings as the new baseline (sorted, counted)."""
    path = path or BASELINE_PATH
    counts: Counter = Counter(f.key for f in findings)
    entries = [
        {"rule": k[0], "path": k[1], "message": k[2], "count": n}
        for k, n in sorted(counts.items())
    ]
    data = {
        "note": (
            "Grandfathered dtpu-lint findings. SHRINK-ONLY: PRs may "
            "remove entries (by fixing the finding and deleting the "
            "entry) but never add or grow one — new code opts out per "
            "line with '# dtpu: noqa[RULE] <reason>' instead. "
            "Regenerate after fixes: python -m tools.dtpu_lint "
            "--write-baseline"
        ),
        "entries": entries,
    }
    Path(path).write_text(json.dumps(data, indent=1) + "\n")
    return data


def apply_baseline(findings: Sequence[Finding], baseline: Counter) -> BaselineDiff:
    """Split findings into (beyond-baseline, stale-entry) buckets.

    Per key the first ``granted`` findings are grandfathered;
    overflow (highest line numbers first kept as NEW so the newest
    call site is what gets reported) fails the gate. A key granted
    more than currently seen is stale — the finding was fixed but the
    entry kept, which would silently re-admit a regression."""
    diff = BaselineDiff()
    by_key: dict = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    for key, fs in by_key.items():
        granted = baseline.get(key, 0)
        if len(fs) > granted:
            ordered = sorted(fs, key=lambda f: f.line)
            diff.new.extend(ordered[granted:])
    for key, granted in baseline.items():
        seen = len(by_key.get(key, ()))
        if seen < granted:
            diff.stale.append((key, granted, seen))
    diff.new.sort(key=lambda f: (f.path, f.line, f.rule))
    diff.stale.sort()
    return diff
