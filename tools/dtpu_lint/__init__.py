"""dtpu-lint: JAX/TPU-aware static analysis for this repo.

The control plane is performance-critical glue where a single blocking
call, per-token host sync, or unbounded metric label silently destroys
throughput — the bug class that passes unit tests and only shows up
under load. dtpu-lint encodes those invariants as enforceable AST
rules instead of reviewer folklore:

- **DTPU001** blocking calls inside ``async def`` on the data plane
- **DTPU002** host↔device syncs/transfers in serve/ops hot paths
- **DTPU003** recompile hazards (unbucketed jit cache keys, jit-in-loop)
- **DTPU004** metric hygiene (docs coverage + bounded label values)
- **DTPU005** settings drift (undocumented ``DTPU_*`` env reads)
- **DTPU006** silent broad excepts in background/routing code
- **DTPU007** 429/503 responses without ``Retry-After``

Interprocedural rules over the shared flow layer (``flow.py``:
project-wide call graph + held-resource tracking across ``await``
boundaries, content-hash cached):

- **DTPU008** exclusive resource held across a blocking await
  (the PR 7 claim-pool deadlock shape, generalized)
- **DTPU009** lock discipline: nested/ABBA/blocking-under-held
  acquisitions across the entity-lock namespaces
- **DTPU010** cancellation safety: tracked acquisitions must release
  in a ``try/finally`` (or ride a context manager)
- **DTPU011** fault-point boundary coverage: raw I/O must sit under a
  ``faults.fire`` point and map ``OSError`` to a typed error
  (the PR 5 unmapped transport error, generalized)

Run repo-wide: ``python -m tools.dtpu_lint`` (tier-1 gate via
``tests/tools/test_dtpu_lint.py``). Opt a line out with
``# dtpu: noqa[DTPU002] <reason>``; grandfathered findings live in
``tools/dtpu_lint/baseline.json`` (shrink-only — see
``docs/reference/lint.md``).
"""

from tools.dtpu_lint.core import (  # noqa: F401
    Finding,
    FileRule,
    ProjectRule,
    RULES,
    all_rules,
    apply_baseline,
    check_file_source,
    load_baseline,
    register,
    run_lint,
    write_baseline,
)

# importing the package registers every rule
import tools.dtpu_lint.rules  # noqa: F401,E402
