"""SARIF 2.1.0 rendering for CI artifacts.

SARIF (Static Analysis Results Interchange Format, OASIS standard) is
what code-scanning UIs ingest; emitting it lets CI upload
``lint.sarif`` and annotate PR diffs with dtpu-lint findings without
any custom glue. Only the minimal valid subset is produced: one run,
the rule catalog as ``tool.driver.rules``, one ``result`` per finding
with a physical location. ``level`` is ``error`` for findings beyond
the baseline and ``note`` for grandfathered ones (both are included so
the artifact shows the full picture; the exit code still keys off the
baseline diff alone).
"""

from typing import Iterable, Optional, Sequence

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def render_sarif(
    new: Sequence,
    grandfathered: Sequence = (),
    rules: Optional[dict] = None,
    base_uri: Optional[str] = None,
) -> dict:
    """Findings → a SARIF 2.1.0 log dict (``json.dumps``-ready)."""
    rule_ids = sorted(
        {f.rule for f in new}
        | {f.rule for f in grandfathered}
        | (set(rules) if rules else set())
    )
    driver: dict = {
        "name": "dtpu-lint",
        "informationUri": "docs/reference/lint.md",
        "rules": [
            {
                "id": rid,
                "shortDescription": {
                    "text": getattr(
                        (rules or {}).get(rid), "name", rid
                    )
                    or rid
                },
            }
            for rid in rule_ids
        ],
    }
    run: dict = {
        "tool": {"driver": driver},
        "results": [
            *(_result(f, "error") for f in new),
            *(_result(f, "note") for f in grandfathered),
        ],
    }
    if base_uri:
        run["originalUriBaseIds"] = {
            "REPOROOT": {"uri": base_uri}
        }
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }


def _result(f, level: str) -> dict:
    return {
        "ruleId": f.rule,
        "level": level,
        "message": {"text": f.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, int(f.line))},
                }
            }
        ],
    }


def validate_minimal(log: dict) -> list:
    """Structural check against the SARIF 2.1.0 required shape —
    returns a list of problems (empty = valid subset). Used by the
    tier-1 test so CI never uploads an artifact scanners reject; the
    full JSON Schema validation runs too when ``jsonschema`` is
    importable."""
    problems = []
    if log.get("version") != SARIF_VERSION:
        problems.append("version must be '2.1.0'")
    runs = log.get("runs")
    if not isinstance(runs, list) or not runs:
        problems.append("runs must be a non-empty array")
        return problems
    for i, run in enumerate(runs):
        driver = (run.get("tool") or {}).get("driver") or {}
        if not driver.get("name"):
            problems.append(f"runs[{i}].tool.driver.name missing")
        for j, res in enumerate(run.get("results", ())):
            if not isinstance(res.get("message", {}).get("text"), str):
                problems.append(f"runs[{i}].results[{j}].message.text missing")
            if "ruleId" not in res:
                problems.append(f"runs[{i}].results[{j}].ruleId missing")
            for loc in res.get("locations", ()):
                art = (loc.get("physicalLocation") or {}).get(
                    "artifactLocation"
                ) or {}
                if not isinstance(art.get("uri"), str):
                    problems.append(
                        f"runs[{i}].results[{j}] location uri missing"
                    )
    return problems


def iter_results(log: dict) -> Iterable[dict]:
    for run in log.get("runs", ()):
        yield from run.get("results", ())
