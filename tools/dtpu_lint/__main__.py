"""CLI: ``python -m tools.dtpu_lint [paths...]``.

Exit 0 when every finding is grandfathered (baseline) or pragma'd;
exit 1 on findings beyond the baseline OR stale baseline entries
(shrink-only policy — see docs/reference/lint.md). ``--format json``
emits machine-readable findings for editor/CI integration.
"""

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

# runnable from anywhere: `python tools/dtpu_lint` resolves imports
# relative to the repo root
_REPO = Path(__file__).resolve().parent.parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.dtpu_lint.core import (  # noqa: E402
    BASELINE_PATH,
    REPO,
    all_rules,
    apply_baseline,
    iter_lint_files,
    load_baseline,
    run_lint,
    write_baseline,
)


def _emit(text: str, output) -> None:
    if output is None:
        print(text)
    else:
        Path(output).write_text(text + "\n")


def _changed_paths(ref: str):
    """Lintable .py files changed vs ``ref`` plus untracked ones, or
    None on git failure (exit 2). Deleted files are filtered — linting
    them would die on read."""
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--diff-filter=d", ref, "--"],
            cwd=REPO, capture_output=True, text=True, check=True,
        ).stdout.splitlines()
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO, capture_output=True, text=True, check=True,
        ).stdout.splitlines()
    except (OSError, subprocess.CalledProcessError) as e:
        print(f"dtpu_lint: git diff vs {ref!r} failed: {e}", file=sys.stderr)
        return None
    return sorted(
        p
        for p in {*diff, *untracked}
        if p.endswith(".py") and (REPO / p).exists()
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dtpu_lint",
        description="JAX/TPU-aware static analysis (rule catalog: "
        "docs/reference/lint.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the shipped package, with "
        "baseline + stale-entry enforcement)",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text"
    )
    ap.add_argument(
        "--output",
        type=Path,
        help="write the json/sarif report to this file instead of stdout "
        "(the CI artifact path, e.g. lint.sarif)",
    )
    ap.add_argument(
        "--changed-only",
        nargs="?",
        const="HEAD",
        metavar="GITREF",
        help="lint only files changed vs GITREF (default HEAD), plus "
        "untracked ones — the fast pre-commit pass; file rules plus "
        "path-scoped project rules (DTPU012-014) whose scope matches a "
        "changed file, baseline restricted to the scanned files like "
        "any path subset",
    )
    ap.add_argument(
        "--baseline",
        type=Path,
        default=BASELINE_PATH,
        help="baseline file (default: tools/dtpu_lint/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, grandfathered or not",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="persist current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--rules", help="comma-separated rule ids to run (default: all)"
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}  {rule.name}")
        return 0

    rule_ids = (
        [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    if args.changed_only:
        if args.paths:
            print(
                "--changed-only computes the path list itself; drop the "
                "explicit paths",
                file=sys.stderr,
            )
            return 2
        changed = _changed_paths(args.changed_only)
        if changed is None:
            return 2
        if not changed:
            print("dtpu-lint: no lintable files changed")
            return 0
        args.paths = changed
    if args.write_baseline and (args.paths or rule_ids):
        # a subset run would overwrite the full baseline with only the
        # subset's findings, silently un-grandfathering everything else
        print(
            "--write-baseline requires a full run (no paths, no --rules)",
            file=sys.stderr,
        )
        return 2
    try:
        findings = run_lint(
            REPO, paths=args.paths or None, rule_ids=rule_ids
        )
    except ValueError as e:
        print(f"dtpu_lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(findings, args.baseline)
        print(
            f"baseline written: {len(findings)} finding(s) → {args.baseline}",
            file=sys.stderr,
        )
        return 0

    if args.no_baseline:
        new, stale = list(findings), []
    else:
        # subset runs (paths and/or --rules) compare against the
        # baseline RESTRICTED to what was actually scanned — keys are
        # (rule, path, message), so per-key counts reconcile exactly
        # for whole-file subsets; an unrestricted baseline would
        # report every other rule/file's entries as stale
        baseline = load_baseline(args.baseline)
        if rule_ids or args.paths:
            rels = (
                set(iter_lint_files(REPO, args.paths))
                if args.paths
                else None
            )
            baseline = Counter(
                {
                    k: n
                    for k, n in baseline.items()
                    if (
                        rule_ids is None
                        or k[0] in rule_ids
                        or k[0].split("-")[0] in rule_ids
                    )
                    and (rels is None or k[1] in rels)
                }
            )
        diff = apply_baseline(findings, baseline)
        new, stale = diff.new, diff.stale

    if args.format == "sarif":
        from tools.dtpu_lint.sarif import render_sarif

        new_set = set(new)
        grandfathered = [f for f in findings if f not in new_set]
        log = render_sarif(new, grandfathered, rules=all_rules())
        _emit(json.dumps(log, indent=1), args.output)
        return 1 if (new or stale) else 0

    if args.format == "json":
        _emit(
            json.dumps(
                {
                    "findings": [f.to_json() for f in new],
                    "stale_baseline": [
                        {
                            "rule": k[0],
                            "path": k[1],
                            "message": k[2],
                            "granted": granted,
                            "seen": seen,
                        }
                        for k, granted, seen in stale
                    ],
                },
                indent=1,
            ),
            args.output,
        )
        return 1 if (new or stale) else 0

    for f in new:
        print(f.render(), file=sys.stderr)
    for key, granted, seen in stale:
        print(
            f"stale baseline entry ({key[0]} {key[1]}: granted {granted}, "
            f"now {seen}): shrink the entry — baseline is shrink-only",
            file=sys.stderr,
        )
    if new or stale:
        print(
            f"\n{len(new)} finding(s) beyond baseline, {len(stale)} stale "
            "baseline entr(ies). Fix the code, opt out with "
            "'# dtpu: noqa[RULE] <reason>', or (stale) prune "
            "tools/dtpu_lint/baseline.json. Catalog: docs/reference/lint.md",
            file=sys.stderr,
        )
        return 1
    n = len(findings)
    print(f"dtpu-lint clean ({n} grandfathered finding(s) in baseline)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
