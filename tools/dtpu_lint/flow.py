"""Interprocedural flow analysis for the async control plane.

The per-file rules (DTPU001-007) catch single-function defects; the
two worst concurrency bugs this repo has shipped were *cross-function*
shapes invisible to them:

- the PR 7 pool deadlock: ``claim_batch`` held a connection from the
  SAME asyncpg pool its callers' body queries acquired from — 15
  concurrent claimants held all 8 connections while their bodies
  waited on the pool, a hard deadlock only the 1500-job bench hit;
- the PR 5 unmapped transport error: ``aiohttp`` raised a raw
  ``OSError`` two frames below the reconciler, which had handlers for
  ``ClientConnectionError``/timeouts only — the tick crashed instead
  of entering the unreachable-agent path.

This module gives ProjectRules the project-wide facts those bug
classes need (RacerD-style lock/resource discipline, applied to
asyncio):

- a **symbol table** over the analyzed packages (module-level
  functions + class methods, import aliases),
- a **call graph** with pragmatic resolution: ``self.x`` binds to the
  enclosing class, ``module.fn`` through import aliases, and bare
  method names fall back to a by-name union over project classes
  (conservative over-approximation — good for "does this await
  transitively reach X" facts),
- per-function **event streams** (with-enter/exit, awaits, yields,
  resource acquire/release, try/finally shape, raw I/O sites, fault
  fires) extracted once per file and **cached on disk keyed by file
  content hash** (plus an analyzer-version salt), so warm runs skip
  parsing entirely,
- fixpoint **facts**: reaches-retry, reaches-network-RPC, pool tokens
  acquired, lock namespaces acquired, resources held across an
  ``asynccontextmanager``'s yield, and fault-point coverage.

Rules DTPU008-011 (rules/resource_await.py, lock_discipline.py,
cancel_safety.py, fault_coverage.py) are thin evaluations over these
facts. Tests exercise them on synthetic fixture *trees* by pointing
:func:`get_flow` at a temp root — nothing here hardcodes the real
repo beyond the default package globs.

Source-site pragmas: an acquisition line carrying
``# dtpu: noqa[DTPU008]`` (or the rule in question) is excluded at the
*propagation source* — e.g. ``PostgresDatabase._conn`` re-acquires the
query pool by design (a tx contextvar diverts to the held connection),
and the pragma there silences every transitive re-acquisition report
instead of requiring one per caller.
"""

from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional, Sequence

from tools.dtpu_lint.core import _PRAGMA_RE

#: packages indexed for symbol resolution (call targets resolve across
#: all of these)
ANALYZED_GLOBS = (
    "dstack_tpu/server/**/*.py",
    "dstack_tpu/routing/**/*.py",
    "dstack_tpu/gateway/**/*.py",
    "dstack_tpu/faults/**/*.py",
    "dstack_tpu/qos/**/*.py",
    "dstack_tpu/utils/**/*.py",
    # the serve data plane's async edge: indexed so DTPU010 can check
    # its slot-acquire/deadline-abort/refund paths (the jax engine
    # below it is sync and stays out of flow analysis)
    "dstack_tpu/serve/openai_server.py",
)

#: paths where findings are REPORTED (the async control plane; testing
#: doubles and the wire-protocol internals below the fault boundary are
#: indexed for resolution but never reported on)
REPORT_GLOBS = (
    "dstack_tpu/server/**/*.py",
    "dstack_tpu/routing/**/*.py",
    "dstack_tpu/gateway/**/*.py",
    "dstack_tpu/faults/**/*.py",
)
REPORT_EXCLUDE = (
    "dstack_tpu/server/testing/**/*.py",
    "dstack_tpu/server/pg_wire.py",
)

CACHE_PATH = Path(__file__).resolve().parent / ".flowcache.json"

#: retry drivers: any call whose final name is one of these makes the
#: calling function a retry site (utils/retry.py's public API)
RETRY_NAMES = frozenset(
    {"retry_async", "retry_sync", "wait_for_async", "wait_for_sync"}
)

#: non-blocking (SKIP-LOCKED-style) lock constructs: namespace = arg0
CLAIM_NAMES = frozenset({"claim_one", "claim_batch"})
#: blocking lock constructs (wait until free): namespace = arg0
BLOCKING_LOCK_NAMES = frozenset({"lock_ctx"})
#: context managers that hold a QoS bucket charge / an engine slot for
#: their body (the ctx idiom for those resources; imperative
#: try_acquire/refund-style charges are DTPU010's domain)
BUCKET_HOLD_NAMES = frozenset({"charged", "charge_ctx"})
SLOT_HOLD_NAMES = frozenset({"hold_slot", "slot_ctx"})

#: network I/O call patterns: (final attr, receiver substring or None)
_NET_FINALS = frozenset(
    {"request", "ws_connect", "get", "post", "put", "delete", "patch"}
)
_DB_IO_FINALS = frozenset({"fetch", "fetchrow", "fetchval", "executemany"})

#: resource acquire -> release pairings for cancellation-safety
#: (final call names; "claim" is special-cased to the wakeups module)
ACQUIRE_RELEASE = {
    "try_claim": ("release",),
    "try_acquire": ("refund",),
    "acquire": ("release",),
}


def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


def _analyzer_version() -> str:
    """Content hash of the analysis code itself: editing flow.py or a
    flow rule invalidates every cached summary."""
    here = Path(__file__).resolve().parent
    parts = []
    for p in sorted([here / "flow.py", *sorted((here / "rules").glob("*.py"))]):
        try:
            parts.append(p.read_bytes())
        except OSError:
            pass
    return _sha1(b"\0".join(parts))[:16]


_ANALYZER_VERSION: Optional[str] = None


def analyzer_version() -> str:
    global _ANALYZER_VERSION
    if _ANALYZER_VERSION is None:
        _ANALYZER_VERSION = _analyzer_version()
    return _ANALYZER_VERSION


# ---------------------------------------------------------------------------
# pass 1: per-file summary extraction (pure function of source text)
# ---------------------------------------------------------------------------


def callee_str(node: ast.AST) -> Optional[str]:
    """Dotted rendering of a call target: ``a.b.c``, ``self.x``, and
    call-chains like ``get_locker().lock_ctx`` (calls render as
    ``()``); anything else (subscripts, literals) is None."""
    parts: list[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            inner = callee_str(node.func)
            if inner is None:
                return None
            parts.append(inner + "()")
            return ".".join(reversed(parts))
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        elif isinstance(node, ast.Await):
            node = node.value
        else:
            return None


def _arg0_literal(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def _line_pragmas(lines: Sequence[str], lineno: int) -> list[str]:
    """Rule ids noqa'd on this line or the comment/decorator block
    directly above it (same placement contract as core.suppressed)."""
    from tools.dtpu_lint.core import pragma_lines

    out: set = set()
    for text in pragma_lines(lines, lineno):
        m = _PRAGMA_RE.search(text)
        if m:
            out.update(
                r.strip().upper()
                for r in m.group("rules").split(",")
                if r.strip()
            )
    return sorted(out)


class _FuncExtractor(ast.NodeVisitor):
    """Linearizes ONE function body into an event stream. Does not
    descend into nested function definitions (they get their own
    summaries)."""

    def __init__(self, lines: Sequence[str]):
        self.lines = lines
        self.events: list[dict] = []
        self.fires: list[str] = []
        self.fires_any = False
        self._fin_depth = 0
        self._handler_stack: list[list[str]] = []

    # -- helpers --

    def _ev(self, kind: str, line: int, **kw) -> dict:
        ev = {"k": kind, "line": line, "fin": self._fin_depth > 0, **kw}
        prag = _line_pragmas(self.lines, line)
        if prag:
            ev["noqa"] = prag
        self.events.append(ev)
        return ev

    def _enclosing_handlers(self) -> list[str]:
        out: list[str] = []
        for hs in self._handler_stack:
            out.extend(hs)
        return out

    def _record_call(self, call: ast.Call, awaited: bool) -> None:
        callee = callee_str(call.func)
        if callee is None:
            self.generic_visit(call)
            return
        final = callee.rsplit(".", 1)[-1]
        line = call.lineno
        # fault fires
        if final in ("fire", "afire", "mutate") and (
            callee.startswith("faults.") or callee == final
        ):
            self.fires_any = True
            lit = _arg0_literal(call)
            if lit:
                self.fires.append(lit)
        # fault_point= keyword indirection (agent_client-style)
        for kw in call.keywords:
            if kw.arg == "fault_point" and isinstance(kw.value, ast.Constant):
                if isinstance(kw.value.value, str):
                    self.fires_any = True
                    self.fires.append(kw.value.value)
        self._ev(
            "await" if awaited else "call",
            line,
            callee=callee,
            arg0=_arg0_literal(call),
            handlers=self._enclosing_handlers(),
        )
        # descend into arguments (nested calls inside args still count)
        for a in call.args:
            self.visit(a)
        for kw in call.keywords:
            self.visit(kw.value)

    # -- structure --

    def visit_FunctionDef(self, node):  # nested defs: own summary
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Await(self, node):
        if isinstance(node.value, ast.Call):
            self._record_call(node.value, awaited=True)
        else:
            self.generic_visit(node)

    def visit_Call(self, node):
        self._record_call(node, awaited=False)

    def _visit_with(self, node, is_async: bool) -> None:
        entered = []
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                callee = callee_str(item.context_expr.func)
                ev = self._ev(
                    "enter",
                    item.context_expr.lineno,
                    callee=callee,
                    arg0=_arg0_literal(item.context_expr),
                    awaited=is_async,
                    handlers=self._enclosing_handlers(),
                )
                entered.append(ev)
                for a in item.context_expr.args:
                    self.visit(a)
                for kw in item.context_expr.keywords:
                    self.visit(kw.value)
            else:
                self.visit(item.context_expr)
                entered.append(None)
        for stmt in node.body:
            self.visit(stmt)
        for ev in reversed(entered):
            if ev is not None:
                self._ev("exit", node.body[-1].end_lineno or ev["line"],
                         callee=ev.get("callee"))

    def visit_With(self, node):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node):
        self._visit_with(node, is_async=True)

    def visit_Try(self, node):
        handler_names: list[str] = []
        for h in node.handlers:
            t = h.type
            if t is None:
                handler_names.append("BaseException")  # bare except
            elif isinstance(t, ast.Tuple):
                handler_names.extend(
                    callee_str(e) or "?" for e in t.elts
                )
            else:
                handler_names.append(callee_str(t) or "?")
        self._handler_stack.append(handler_names)
        for stmt in node.body:
            self.visit(stmt)
        self._handler_stack.pop()
        for h in node.handlers:
            for stmt in h.body:
                self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        if node.finalbody:
            self._fin_depth += 1
            for stmt in node.finalbody:
                self.visit(stmt)
            self._fin_depth -= 1

    def visit_Yield(self, node):
        self._ev("yield", node.lineno)
        self.generic_visit(node)

    def visit_YieldFrom(self, node):
        self._ev("yield", node.lineno)
        self.generic_visit(node)

    def visit_Return(self, node):
        self._ev("return", node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        tgt = callee_str(node.target)
        if tgt is not None:
            low = tgt.rsplit(".", 1)[-1].lower()
            if "inflight" in low or "outstanding" in low or "refs" == low:
                op = "+" if isinstance(node.op, ast.Add) else "-"
                self._ev("aug", node.lineno, target=tgt, op=op)
        self.generic_visit(node)


def _decorator_names(node) -> list[str]:
    out = []
    for d in node.decorator_list:
        s = callee_str(d.func if isinstance(d, ast.Call) else d)
        if s:
            out.append(s.rsplit(".", 1)[-1])
    return out


def extract_summary(src: str, relpath: str) -> dict:
    """Pure per-file pass: imports + one summary per function. This is
    what the on-disk cache stores, keyed by the file's content hash."""
    tree = ast.parse(src, filename=relpath)
    lines = src.splitlines()
    imports: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                imports[a.asname or a.name] = f"{node.module}.{a.name}"

    functions: list[dict] = []

    def _walk_body(body, cls: Optional[str], prefix: str):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ex = _FuncExtractor(lines)
                for stmt in node.body:
                    ex.visit(stmt)
                decos = _decorator_names(node)
                functions.append(
                    {
                        "name": node.name,
                        "qual": f"{prefix}{node.name}",
                        "cls": cls,
                        "line": node.lineno,
                        "is_async": isinstance(node, ast.AsyncFunctionDef),
                        "is_acm": "asynccontextmanager" in decos
                        or "contextmanager" in decos,
                        "events": ex.events,
                        "fires": sorted(set(ex.fires)),
                        "fires_any": ex.fires_any,
                    }
                )
                # nested defs become their own (unresolvable) summaries
                _walk_body(
                    node.body, cls, f"{prefix}{node.name}.<locals>."
                )
            elif isinstance(node, ast.ClassDef):
                _walk_body(node.body, node.name, f"{node.name}.")

    _walk_body(tree.body, None, "")
    return {"path": relpath, "imports": imports, "functions": functions}


# ---------------------------------------------------------------------------
# pass 2: project index + resolution + fact fixpoints
# ---------------------------------------------------------------------------

#: method/function names too generic to resolve by project-wide name
#: union (the fallback when no better binding exists)
_UNION_BLOCKLIST = frozenset(
    {
        "get", "set", "add", "pop", "items", "values", "keys", "close",
        "update", "remove", "append", "extend", "join", "read", "write",
        "send", "put", "text", "json", "copy", "strip", "split", "format",
        "encode", "decode", "info", "debug", "warning", "error", "exception",
        "inc", "observe", "isoformat", "model_dump", "model_validate",
        "dumps", "loads", "family", "render", "start", "commit", "rollback",
        "wait", "cancel", "result", "done", "sleep", "gather", "create_task",
    }
)


@dataclass
class FuncInfo:
    key: str  # "relpath::Qual.name"
    path: str
    summary: dict
    # computed facts
    reaches_retry: bool = False
    reaches_rpc: bool = False
    pool_tokens: frozenset = frozenset()
    lock_reach: frozenset = frozenset()  # (namespace, blocking)
    holds: frozenset = frozenset()  # tokens held across this acm's yield
    covered: bool = False  # under a fault point (self or all callers)
    callees: set = field(default_factory=set)
    callers: set = field(default_factory=set)


def _is_net_io(callee: str) -> bool:
    final = callee.rsplit(".", 1)[-1]
    recv = callee[: -len(final) - 1] if "." in callee else ""
    if callee in ("asyncio.open_connection",) or final == "create_connection":
        return True
    # the receiver's LAST segment must be session-like: `self._sessions`
    # is a dict of sessions and `.get()` on it is a lookup, not I/O
    last = recv.split(".")[-1].lower()
    if final in _NET_FINALS and last in ("session", "_session", "session()"):
        return True
    if callee.startswith("aiohttp.request"):
        return True
    return False


def _is_db_io(callee: str) -> bool:
    final = callee.rsplit(".", 1)[-1]
    recv = callee[: -len(final) - 1] if "." in callee else ""
    return final in _DB_IO_FINALS and recv.split(".")[-1] in ("conn", "_conn")


def _pool_token(callee: str, cls: Optional[str]) -> Optional[str]:
    """``<expr>.acquire()`` on a pool-ish receiver → a class-qualified
    token so ``self._pool`` in different classes never collides."""
    final = callee.rsplit(".", 1)[-1]
    if final != "acquire":
        return None
    recv = callee[: -len(final) - 1]
    if "pool" not in recv.lower():
        return None
    return f"{cls or '<module>'}::{recv}"


class ProjectFlow:
    """The resolved project: symbol table, call graph, facts."""

    def __init__(self, root: Path, summaries: list[dict]):
        self.root = root
        self.funcs: dict[str, FuncInfo] = {}
        self.by_name: dict[str, list[str]] = {}
        self.by_method: dict[tuple, list[str]] = {}  # (cls, name) -> keys
        self.module_symbols: dict[tuple, str] = {}  # (modpath, name) -> key
        self.imports: dict[str, dict] = {}
        self.summaries = summaries
        for s in summaries:
            self.imports[s["path"]] = s.get("imports", {})
            for f in s["functions"]:
                key = f"{s['path']}::{f['qual']}"
                fi = FuncInfo(key=key, path=s["path"], summary=f)
                self.funcs[key] = fi
                self.by_name.setdefault(f["name"], []).append(key)
                if f["cls"]:
                    self.by_method.setdefault(
                        (f["cls"], f["name"]), []
                    ).append(key)
                else:
                    self.module_symbols[(s["path"], f["name"])] = key
        self._resolve_cache: dict = {}
        self._build_graph()
        self._fixpoints()

    # -- resolution --

    def _module_for(self, dotted_module: str) -> Optional[str]:
        """'dstack_tpu.server.db' -> 'dstack_tpu/server/db.py' when
        indexed."""
        rel = dotted_module.replace(".", "/")
        for cand in (f"{rel}.py", f"{rel}/__init__.py"):
            if any(s["path"] == cand for s in self.summaries):
                return cand
        return None

    def resolve(self, path: str, cls: Optional[str], callee: str) -> list[str]:
        """Call target → candidate FuncInfo keys (possibly empty)."""
        ck = (path, cls, callee)
        if ck in self._resolve_cache:
            return self._resolve_cache[ck]
        out: list[str] = []
        parts = callee.split(".")
        final = parts[-1]
        if callee.startswith("self.") and cls is not None and len(parts) == 2:
            out = list(self.by_method.get((cls, final), []))
            if not out:
                out = self._union(final)
        elif len(parts) == 1:
            # bare name: module-level symbol, then import alias, then union
            key = self.module_symbols.get((path, final))
            if key:
                out = [key]
            else:
                imp = self.imports.get(path, {}).get(final)
                if imp and "." in imp:
                    mod, name = imp.rsplit(".", 1)
                    mpath = self._module_for(mod)
                    if mpath:
                        k = self.module_symbols.get((mpath, name))
                        if k:
                            out = [k]
                if not out:
                    out = self._union(final)
        else:
            # dotted: resolve the root through import aliases
            root_name = parts[0].split("()")[0]
            imp = self.imports.get(path, {}).get(root_name)
            resolved = False
            if imp and len(parts) == 2:
                mpath = self._module_for(imp)
                if mpath:
                    k = self.module_symbols.get((mpath, final))
                    out = [k] if k else []
                    resolved = True
            if not resolved:
                out = self._union(final)
        self._resolve_cache[ck] = out
        return out

    def _union(self, name: str) -> list[str]:
        if name in _UNION_BLOCKLIST:
            return []
        return list(self.by_name.get(name, []))

    # -- graph + fixpoints --

    def _build_graph(self) -> None:
        for fi in self.funcs.values():
            f = fi.summary
            for ev in f["events"]:
                if ev["k"] in ("await", "call", "enter") and ev.get("callee"):
                    for tgt in self.resolve(fi.path, f["cls"], ev["callee"]):
                        fi.callees.add(tgt)
                        self.funcs[tgt].callers.add(fi.key)
            # a closure inherits its enclosing function as a caller:
            # `_exec` handed to `self._run(_exec)` is never *called*
            # syntactically, but runs under the outer function's fault
            # coverage
            if ".<locals>." in f["qual"]:
                outer_qual = f["qual"].rsplit(".<locals>.", 1)[0]
                outer = f"{fi.path}::{outer_qual}"
                if outer in self.funcs:
                    fi.callers.add(outer)
                    self.funcs[outer].callees.add(fi.key)

    def _fixpoints(self) -> None:
        # seed local facts
        for fi in self.funcs.values():
            f = fi.summary
            tokens: set = set()
            locks: set = set()
            retry = rpc = False
            for ev in f["events"]:
                callee = ev.get("callee")
                if not callee or ev["k"] not in ("await", "call", "enter"):
                    continue
                final = callee.rsplit(".", 1)[-1]
                if final in RETRY_NAMES:
                    retry = True
                if _is_net_io(callee):
                    rpc = True
                tok = _pool_token(callee, f["cls"])
                rule_noqa = set(ev.get("noqa", ()))
                if tok and "DTPU008" not in rule_noqa:
                    tokens.add(tok)
                if final in CLAIM_NAMES and "DTPU009" not in rule_noqa:
                    locks.add((ev.get("arg0"), False))
                elif final in BLOCKING_LOCK_NAMES and "DTPU009" not in rule_noqa:
                    locks.add((ev.get("arg0"), True))
            fi.reaches_retry = retry
            fi.reaches_rpc = rpc
            fi.pool_tokens = frozenset(tokens)
            fi.lock_reach = frozenset(locks)
            fi.covered = f["fires_any"]

        # propagate reaches_* / pool_tokens / lock_reach up the graph
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                for tgt in fi.callees:
                    g = self.funcs[tgt]
                    if g.reaches_retry and not fi.reaches_retry:
                        fi.reaches_retry = True
                        changed = True
                    if g.reaches_rpc and not fi.reaches_rpc:
                        fi.reaches_rpc = True
                        changed = True
                    if not g.pool_tokens <= fi.pool_tokens:
                        fi.pool_tokens = fi.pool_tokens | g.pool_tokens
                        changed = True
                    if not g.lock_reach <= fi.lock_reach:
                        fi.lock_reach = fi.lock_reach | g.lock_reach
                        changed = True

        # holds-across-yield for context-manager functions
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                f = fi.summary
                if not f["is_acm"]:
                    continue
                held: set = set()
                at_yield: set = set()
                for ev in f["events"]:
                    k = ev["k"]
                    if k == "enter" and ev.get("callee"):
                        held |= self._direct_hold(fi, ev)
                    elif k == "await" and ev.get("callee"):
                        tok = _pool_token(ev["callee"], f["cls"])
                        if tok and "DTPU008" not in set(ev.get("noqa", ())):
                            held.add(("pool", tok))
                    elif k == "yield":
                        at_yield |= held
                if at_yield != set(fi.holds):
                    fi.holds = frozenset(at_yield)
                    changed = True

        # fault coverage: covered if self fires, or every caller covered
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                if fi.covered or not fi.callers:
                    continue
                if all(self.funcs[c].covered for c in fi.callers):
                    fi.covered = True
                    changed = True

    def _direct_hold(self, fi: FuncInfo, ev: dict) -> set:
        """Resource tokens a with-item context installs, including
        those an asynccontextmanager holds across its yield."""
        callee = ev["callee"]
        final = callee.rsplit(".", 1)[-1]
        held: set = set()
        noqa = set(ev.get("noqa", ()))
        if "DTPU008" in noqa:
            return held
        if final == "transaction":
            held.add(("tx", callee))
        elif final in CLAIM_NAMES:
            held.add(("claim", ev.get("arg0") or callee))
        elif final in BUCKET_HOLD_NAMES:
            held.add(("bucket", callee))
        elif final in SLOT_HOLD_NAMES:
            held.add(("slot", callee))
        for tgt in self.resolve(fi.path, fi.summary["cls"], callee):
            g = self.funcs[tgt]
            if g.summary["is_acm"]:
                held |= set(g.holds)
        return held

    # -- convenience for rules --

    def functions(self) -> Iterable[FuncInfo]:
        return self.funcs.values()

    def callee_facts(self, fi: FuncInfo, callee: str) -> list["FuncInfo"]:
        return [
            self.funcs[k]
            for k in self.resolve(fi.path, fi.summary["cls"], callee)
        ]


# ---------------------------------------------------------------------------
# entry point + caching
# ---------------------------------------------------------------------------


def _glob_many(root: Path, globs: Sequence[str]) -> list[str]:
    rels: set = set()
    for g in globs:
        rels.update(p.relative_to(root).as_posix() for p in root.glob(g))
    return sorted(rels)


def report_paths(root: Path) -> set:
    from tools.dtpu_lint.core import glob_match

    out = set()
    for rel in _glob_many(root, REPORT_GLOBS):
        if not any(glob_match(rel, g) for g in REPORT_EXCLUDE):
            out.add(rel)
    return out


def _load_cache(path: Path) -> dict:
    try:
        data = json.loads(path.read_text())
        if data.get("version") == analyzer_version():
            return data.get("files", {})
    except (OSError, ValueError):
        pass
    return {}


def _store_cache(path: Path, files: dict) -> None:
    try:
        path.write_text(
            json.dumps({"version": analyzer_version(), "files": files})
        )
    except OSError:
        pass  # read-only checkout: cache is an optimization only


#: in-process memo: root -> (state-digest, ProjectFlow) — four rules
#: in one lint run (and repeated run_lint calls in one pytest session)
#: share a single analysis; one live state per root
_memo: dict = {}


def get_flow(
    root: Path, cache_path: Optional[Path] = CACHE_PATH
) -> ProjectFlow:
    root = Path(root).resolve()
    if cache_path is CACHE_PATH:
        from tools.dtpu_lint.core import REPO

        if root != Path(REPO).resolve():
            # fixture trees (tests) must not churn the shared cache
            cache_path = None
    rels = _glob_many(root, ANALYZED_GLOBS)
    sources: dict[str, bytes] = {}
    digests: dict[str, str] = {}
    for rel in rels:
        try:
            raw = (root / rel).read_bytes()
        except OSError:
            continue
        sources[rel] = raw
        digests[rel] = _sha1(raw)
    state = _sha1(
        json.dumps(sorted(digests.items())).encode()
        + analyzer_version().encode()
    )
    hit = _memo.get(str(root))
    if hit is not None and hit[0] == state:
        return hit[1]

    cached = _load_cache(cache_path) if cache_path else {}
    fresh: dict = {}
    summaries: list[dict] = []
    for rel, raw in sorted(sources.items()):
        d = digests[rel]
        hit = cached.get(d)
        if hit is not None and hit.get("path") == rel:
            summaries.append(hit)
            fresh[d] = hit
            continue
        try:
            summary = extract_summary(raw.decode("utf-8"), rel)
        except (SyntaxError, UnicodeDecodeError):
            continue  # DTPU000 reports unparseable files already
        summaries.append(summary)
        fresh[d] = summary
    if cache_path and fresh != cached:
        _store_cache(cache_path, fresh)

    flow = ProjectFlow(root, summaries)
    _memo[str(root)] = (state, flow)
    return flow
