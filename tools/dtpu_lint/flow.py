"""Interprocedural flow analysis for the async control plane.

The per-file rules (DTPU001-007) catch single-function defects; the
two worst concurrency bugs this repo has shipped were *cross-function*
shapes invisible to them:

- the PR 7 pool deadlock: ``claim_batch`` held a connection from the
  SAME asyncpg pool its callers' body queries acquired from — 15
  concurrent claimants held all 8 connections while their bodies
  waited on the pool, a hard deadlock only the 1500-job bench hit;
- the PR 5 unmapped transport error: ``aiohttp`` raised a raw
  ``OSError`` two frames below the reconciler, which had handlers for
  ``ClientConnectionError``/timeouts only — the tick crashed instead
  of entering the unreachable-agent path.

This module gives ProjectRules the project-wide facts those bug
classes need (RacerD-style lock/resource discipline, applied to
asyncio):

- a **symbol table** over the analyzed packages (module-level
  functions + class methods, import aliases),
- a **call graph** with pragmatic resolution: ``self.x`` binds to the
  enclosing class, ``module.fn`` through import aliases, and bare
  method names fall back to a by-name union over project classes
  (conservative over-approximation — good for "does this await
  transitively reach X" facts),
- per-function **event streams** (with-enter/exit, awaits, yields,
  resource acquire/release, try/finally shape, raw I/O sites, fault
  fires) extracted once per file and **cached on disk keyed by file
  content hash** (plus an analyzer-version salt), so warm runs skip
  parsing entirely,
- fixpoint **facts**: reaches-retry, reaches-network-RPC, pool tokens
  acquired, lock namespaces acquired, resources held across an
  ``asynccontextmanager``'s yield, and fault-point coverage.

Rules DTPU008-011 (rules/resource_await.py, lock_discipline.py,
cancel_safety.py, fault_coverage.py) are thin evaluations over these
facts. Tests exercise them on synthetic fixture *trees* by pointing
:func:`get_flow` at a temp root — nothing here hardcodes the real
repo beyond the default package globs.

Source-site pragmas: an acquisition line carrying
``# dtpu: noqa[DTPU008]`` (or the rule in question) is excluded at the
*propagation source* — e.g. ``PostgresDatabase._conn`` re-acquires the
query pool by design (a tx contextvar diverts to the held connection),
and the pragma there silences every transitive re-acquisition report
instead of requiring one per caller.
"""

from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional, Sequence

from tools.dtpu_lint.core import _PRAGMA_RE

#: packages indexed for symbol resolution (call targets resolve across
#: all of these)
ANALYZED_GLOBS = (
    "dstack_tpu/server/**/*.py",
    "dstack_tpu/routing/**/*.py",
    "dstack_tpu/gateway/**/*.py",
    "dstack_tpu/faults/**/*.py",
    "dstack_tpu/qos/**/*.py",
    "dstack_tpu/utils/**/*.py",
    # the serve data plane's async edge: indexed so DTPU010 can check
    # its slot-acquire/deadline-abort/refund paths (the jax engine
    # below it is sync and stays out of flow analysis)
    "dstack_tpu/serve/openai_server.py",
)

#: paths where findings are REPORTED (the async control plane; testing
#: doubles and the wire-protocol internals below the fault boundary are
#: indexed for resolution but never reported on)
REPORT_GLOBS = (
    "dstack_tpu/server/**/*.py",
    "dstack_tpu/routing/**/*.py",
    "dstack_tpu/gateway/**/*.py",
    "dstack_tpu/faults/**/*.py",
)
REPORT_EXCLUDE = (
    "dstack_tpu/server/testing/**/*.py",
    "dstack_tpu/server/pg_wire.py",
)

CACHE_PATH = Path(__file__).resolve().parent / ".flowcache.json"

#: retry drivers: any call whose final name is one of these makes the
#: calling function a retry site (utils/retry.py's public API)
RETRY_NAMES = frozenset(
    {"retry_async", "retry_sync", "wait_for_async", "wait_for_sync"}
)

#: non-blocking (SKIP-LOCKED-style) lock constructs: namespace = arg0
CLAIM_NAMES = frozenset({"claim_one", "claim_batch"})
#: blocking lock constructs (wait until free): namespace = arg0
BLOCKING_LOCK_NAMES = frozenset({"lock_ctx"})
#: context managers that hold a QoS bucket charge / an engine slot for
#: their body (the ctx idiom for those resources; imperative
#: try_acquire/refund-style charges are DTPU010's domain)
BUCKET_HOLD_NAMES = frozenset({"charged", "charge_ctx"})
SLOT_HOLD_NAMES = frozenset({"hold_slot", "slot_ctx"})

#: network I/O call patterns: (final attr, receiver substring or None)
_NET_FINALS = frozenset(
    {"request", "ws_connect", "get", "post", "put", "delete", "patch"}
)
_DB_IO_FINALS = frozenset({"fetch", "fetchrow", "fetchval", "executemany"})

#: resource acquire -> release pairings for cancellation-safety
#: (final call names; "claim" is special-cased to the wakeups module)
ACQUIRE_RELEASE = {
    "try_claim": ("release",),
    "try_acquire": ("refund",),
    "acquire": ("release",),
}


def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


def _analyzer_version() -> str:
    """Content hash of the analysis code itself: editing flow.py or a
    flow rule invalidates every cached summary."""
    here = Path(__file__).resolve().parent
    parts = []
    for p in sorted([here / "flow.py", *sorted((here / "rules").glob("*.py"))]):
        try:
            parts.append(p.read_bytes())
        except OSError:
            pass
    return _sha1(b"\0".join(parts))[:16]


_ANALYZER_VERSION: Optional[str] = None


def analyzer_version() -> str:
    global _ANALYZER_VERSION
    if _ANALYZER_VERSION is None:
        _ANALYZER_VERSION = _analyzer_version()
    return _ANALYZER_VERSION


# ---------------------------------------------------------------------------
# pass 1: per-file summary extraction (pure function of source text)
# ---------------------------------------------------------------------------


def callee_str(node: ast.AST) -> Optional[str]:
    """Dotted rendering of a call target: ``a.b.c``, ``self.x``, and
    call-chains like ``get_locker().lock_ctx`` (calls render as
    ``()``); anything else (subscripts, literals) is None."""
    parts: list[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            inner = callee_str(node.func)
            if inner is None:
                return None
            parts.append(inner + "()")
            return ".".join(reversed(parts))
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        elif isinstance(node, ast.Await):
            node = node.value
        else:
            return None


def _arg0_literal(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def _line_pragmas(lines: Sequence[str], lineno: int) -> list[str]:
    """Rule ids noqa'd on this line or the comment/decorator block
    directly above it (same placement contract as core.suppressed)."""
    from tools.dtpu_lint.core import pragma_lines

    out: set = set()
    for text in pragma_lines(lines, lineno):
        m = _PRAGMA_RE.search(text)
        if m:
            out.update(
                r.strip().upper()
                for r in m.group("rules").split(",")
                if r.strip()
            )
    return sorted(out)


class _FuncExtractor(ast.NodeVisitor):
    """Linearizes ONE function body into an event stream. Does not
    descend into nested function definitions (they get their own
    summaries)."""

    def __init__(self, lines: Sequence[str]):
        self.lines = lines
        self.events: list[dict] = []
        self.fires: list[str] = []
        self.fires_any = False
        self._fin_depth = 0
        self._handler_stack: list[list[str]] = []

    # -- helpers --

    def _ev(self, kind: str, line: int, **kw) -> dict:
        ev = {"k": kind, "line": line, "fin": self._fin_depth > 0, **kw}
        prag = _line_pragmas(self.lines, line)
        if prag:
            ev["noqa"] = prag
        self.events.append(ev)
        return ev

    def _enclosing_handlers(self) -> list[str]:
        out: list[str] = []
        for hs in self._handler_stack:
            out.extend(hs)
        return out

    def _record_call(self, call: ast.Call, awaited: bool) -> None:
        callee = callee_str(call.func)
        if callee is None:
            self.generic_visit(call)
            return
        final = callee.rsplit(".", 1)[-1]
        line = call.lineno
        # fault fires
        if final in ("fire", "afire", "mutate") and (
            callee.startswith("faults.") or callee == final
        ):
            self.fires_any = True
            lit = _arg0_literal(call)
            if lit:
                self.fires.append(lit)
        # fault_point= keyword indirection (agent_client-style)
        for kw in call.keywords:
            if kw.arg == "fault_point" and isinstance(kw.value, ast.Constant):
                if isinstance(kw.value.value, str):
                    self.fires_any = True
                    self.fires.append(kw.value.value)
        self._ev(
            "await" if awaited else "call",
            line,
            callee=callee,
            arg0=_arg0_literal(call),
            handlers=self._enclosing_handlers(),
        )
        # descend into arguments (nested calls inside args still count)
        for a in call.args:
            self.visit(a)
        for kw in call.keywords:
            self.visit(kw.value)

    # -- structure --

    def visit_FunctionDef(self, node):  # nested defs: own summary
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Await(self, node):
        if isinstance(node.value, ast.Call):
            self._record_call(node.value, awaited=True)
        else:
            self.generic_visit(node)

    def visit_Call(self, node):
        self._record_call(node, awaited=False)

    def _visit_with(self, node, is_async: bool) -> None:
        entered = []
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                callee = callee_str(item.context_expr.func)
                ev = self._ev(
                    "enter",
                    item.context_expr.lineno,
                    callee=callee,
                    arg0=_arg0_literal(item.context_expr),
                    awaited=is_async,
                    handlers=self._enclosing_handlers(),
                )
                entered.append(ev)
                for a in item.context_expr.args:
                    self.visit(a)
                for kw in item.context_expr.keywords:
                    self.visit(kw.value)
            else:
                self.visit(item.context_expr)
                entered.append(None)
        for stmt in node.body:
            self.visit(stmt)
        for ev in reversed(entered):
            if ev is not None:
                self._ev("exit", node.body[-1].end_lineno or ev["line"],
                         callee=ev.get("callee"))

    def visit_With(self, node):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node):
        self._visit_with(node, is_async=True)

    def visit_Try(self, node):
        handler_names: list[str] = []
        for h in node.handlers:
            t = h.type
            if t is None:
                handler_names.append("BaseException")  # bare except
            elif isinstance(t, ast.Tuple):
                handler_names.extend(
                    callee_str(e) or "?" for e in t.elts
                )
            else:
                handler_names.append(callee_str(t) or "?")
        self._handler_stack.append(handler_names)
        for stmt in node.body:
            self.visit(stmt)
        self._handler_stack.pop()
        for h in node.handlers:
            for stmt in h.body:
                self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        if node.finalbody:
            self._fin_depth += 1
            for stmt in node.finalbody:
                self.visit(stmt)
            self._fin_depth -= 1

    def visit_Yield(self, node):
        self._ev("yield", node.lineno)
        self.generic_visit(node)

    def visit_YieldFrom(self, node):
        self._ev("yield", node.lineno)
        self.generic_visit(node)

    def visit_Return(self, node):
        self._ev("return", node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        tgt = callee_str(node.target)
        if tgt is not None:
            low = tgt.rsplit(".", 1)[-1].lower()
            if "inflight" in low or "outstanding" in low or "refs" == low:
                op = "+" if isinstance(node.op, ast.Add) else "-"
                self._ev("aug", node.lineno, target=tgt, op=op)
        self.generic_visit(node)


def _decorator_names(node) -> list[str]:
    out = []
    for d in node.decorator_list:
        s = callee_str(d.func if isinstance(d, ast.Call) else d)
        if s:
            out.append(s.rsplit(".", 1)[-1])
    return out


def extract_summary(src: str, relpath: str) -> dict:
    """Pure per-file pass: imports + one summary per function. This is
    what the on-disk cache stores, keyed by the file's content hash."""
    tree = ast.parse(src, filename=relpath)
    lines = src.splitlines()
    imports: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                imports[a.asname or a.name] = f"{node.module}.{a.name}"

    functions: list[dict] = []

    def _walk_body(body, cls: Optional[str], prefix: str):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ex = _FuncExtractor(lines)
                for stmt in node.body:
                    ex.visit(stmt)
                decos = _decorator_names(node)
                functions.append(
                    {
                        "name": node.name,
                        "qual": f"{prefix}{node.name}",
                        "cls": cls,
                        "line": node.lineno,
                        "is_async": isinstance(node, ast.AsyncFunctionDef),
                        "is_acm": "asynccontextmanager" in decos
                        or "contextmanager" in decos,
                        "events": ex.events,
                        "fires": sorted(set(ex.fires)),
                        "fires_any": ex.fires_any,
                    }
                )
                # nested defs become their own (unresolvable) summaries
                _walk_body(
                    node.body, cls, f"{prefix}{node.name}.<locals>."
                )
            elif isinstance(node, ast.ClassDef):
                _walk_body(node.body, node.name, f"{node.name}.")

    _walk_body(tree.body, None, "")
    return {"path": relpath, "imports": imports, "functions": functions}


# ---------------------------------------------------------------------------
# pass 2: project index + resolution + fact fixpoints
# ---------------------------------------------------------------------------

#: method/function names too generic to resolve by project-wide name
#: union (the fallback when no better binding exists)
_UNION_BLOCKLIST = frozenset(
    {
        "get", "set", "add", "pop", "items", "values", "keys", "close",
        "update", "remove", "append", "extend", "join", "read", "write",
        "send", "put", "text", "json", "copy", "strip", "split", "format",
        "encode", "decode", "info", "debug", "warning", "error", "exception",
        "inc", "observe", "isoformat", "model_dump", "model_validate",
        "dumps", "loads", "family", "render", "start", "commit", "rollback",
        "wait", "cancel", "result", "done", "sleep", "gather", "create_task",
    }
)


@dataclass
class FuncInfo:
    key: str  # "relpath::Qual.name"
    path: str
    summary: dict
    # computed facts
    reaches_retry: bool = False
    reaches_rpc: bool = False
    pool_tokens: frozenset = frozenset()
    lock_reach: frozenset = frozenset()  # (namespace, blocking)
    holds: frozenset = frozenset()  # tokens held across this acm's yield
    covered: bool = False  # under a fault point (self or all callers)
    callees: set = field(default_factory=set)
    callers: set = field(default_factory=set)


def _is_net_io(callee: str) -> bool:
    final = callee.rsplit(".", 1)[-1]
    recv = callee[: -len(final) - 1] if "." in callee else ""
    if callee in ("asyncio.open_connection",) or final == "create_connection":
        return True
    # the receiver's LAST segment must be session-like: `self._sessions`
    # is a dict of sessions and `.get()` on it is a lookup, not I/O
    last = recv.split(".")[-1].lower()
    if final in _NET_FINALS and last in ("session", "_session", "session()"):
        return True
    if callee.startswith("aiohttp.request"):
        return True
    return False


def _is_db_io(callee: str) -> bool:
    final = callee.rsplit(".", 1)[-1]
    recv = callee[: -len(final) - 1] if "." in callee else ""
    return final in _DB_IO_FINALS and recv.split(".")[-1] in ("conn", "_conn")


def _pool_token(callee: str, cls: Optional[str]) -> Optional[str]:
    """``<expr>.acquire()`` on a pool-ish receiver → a class-qualified
    token so ``self._pool`` in different classes never collides."""
    final = callee.rsplit(".", 1)[-1]
    if final != "acquire":
        return None
    recv = callee[: -len(final) - 1]
    if "pool" not in recv.lower():
        return None
    return f"{cls or '<module>'}::{recv}"


class ProjectFlow:
    """The resolved project: symbol table, call graph, facts."""

    def __init__(self, root: Path, summaries: list[dict]):
        self.root = root
        self.funcs: dict[str, FuncInfo] = {}
        self.by_name: dict[str, list[str]] = {}
        self.by_method: dict[tuple, list[str]] = {}  # (cls, name) -> keys
        self.module_symbols: dict[tuple, str] = {}  # (modpath, name) -> key
        self.imports: dict[str, dict] = {}
        self.summaries = summaries
        for s in summaries:
            self.imports[s["path"]] = s.get("imports", {})
            for f in s["functions"]:
                key = f"{s['path']}::{f['qual']}"
                fi = FuncInfo(key=key, path=s["path"], summary=f)
                self.funcs[key] = fi
                self.by_name.setdefault(f["name"], []).append(key)
                if f["cls"]:
                    self.by_method.setdefault(
                        (f["cls"], f["name"]), []
                    ).append(key)
                else:
                    self.module_symbols[(s["path"], f["name"])] = key
        self._resolve_cache: dict = {}
        self._build_graph()
        self._fixpoints()

    # -- resolution --

    def _module_for(self, dotted_module: str) -> Optional[str]:
        """'dstack_tpu.server.db' -> 'dstack_tpu/server/db.py' when
        indexed."""
        rel = dotted_module.replace(".", "/")
        for cand in (f"{rel}.py", f"{rel}/__init__.py"):
            if any(s["path"] == cand for s in self.summaries):
                return cand
        return None

    def resolve(self, path: str, cls: Optional[str], callee: str) -> list[str]:
        """Call target → candidate FuncInfo keys (possibly empty)."""
        ck = (path, cls, callee)
        if ck in self._resolve_cache:
            return self._resolve_cache[ck]
        out: list[str] = []
        parts = callee.split(".")
        final = parts[-1]
        if callee.startswith("self.") and cls is not None and len(parts) == 2:
            out = list(self.by_method.get((cls, final), []))
            if not out:
                out = self._union(final)
        elif len(parts) == 1:
            # bare name: module-level symbol, then import alias, then union
            key = self.module_symbols.get((path, final))
            if key:
                out = [key]
            else:
                imp = self.imports.get(path, {}).get(final)
                if imp and "." in imp:
                    mod, name = imp.rsplit(".", 1)
                    mpath = self._module_for(mod)
                    if mpath:
                        k = self.module_symbols.get((mpath, name))
                        if k:
                            out = [k]
                if not out:
                    out = self._union(final)
        else:
            # dotted: resolve the root through import aliases
            root_name = parts[0].split("()")[0]
            imp = self.imports.get(path, {}).get(root_name)
            resolved = False
            if imp and len(parts) == 2:
                mpath = self._module_for(imp)
                if mpath:
                    k = self.module_symbols.get((mpath, final))
                    out = [k] if k else []
                    resolved = True
            if not resolved:
                out = self._union(final)
        self._resolve_cache[ck] = out
        return out

    def _union(self, name: str) -> list[str]:
        if name in _UNION_BLOCKLIST:
            return []
        return list(self.by_name.get(name, []))

    # -- graph + fixpoints --

    def _build_graph(self) -> None:
        for fi in self.funcs.values():
            f = fi.summary
            for ev in f["events"]:
                if ev["k"] in ("await", "call", "enter") and ev.get("callee"):
                    for tgt in self.resolve(fi.path, f["cls"], ev["callee"]):
                        fi.callees.add(tgt)
                        self.funcs[tgt].callers.add(fi.key)
            # a closure inherits its enclosing function as a caller:
            # `_exec` handed to `self._run(_exec)` is never *called*
            # syntactically, but runs under the outer function's fault
            # coverage
            if ".<locals>." in f["qual"]:
                outer_qual = f["qual"].rsplit(".<locals>.", 1)[0]
                outer = f"{fi.path}::{outer_qual}"
                if outer in self.funcs:
                    fi.callers.add(outer)
                    self.funcs[outer].callees.add(fi.key)

    def _fixpoints(self) -> None:
        # seed local facts
        for fi in self.funcs.values():
            f = fi.summary
            tokens: set = set()
            locks: set = set()
            retry = rpc = False
            for ev in f["events"]:
                callee = ev.get("callee")
                if not callee or ev["k"] not in ("await", "call", "enter"):
                    continue
                final = callee.rsplit(".", 1)[-1]
                if final in RETRY_NAMES:
                    retry = True
                if _is_net_io(callee):
                    rpc = True
                tok = _pool_token(callee, f["cls"])
                rule_noqa = set(ev.get("noqa", ()))
                if tok and "DTPU008" not in rule_noqa:
                    tokens.add(tok)
                if final in CLAIM_NAMES and "DTPU009" not in rule_noqa:
                    locks.add((ev.get("arg0"), False))
                elif final in BLOCKING_LOCK_NAMES and "DTPU009" not in rule_noqa:
                    locks.add((ev.get("arg0"), True))
            fi.reaches_retry = retry
            fi.reaches_rpc = rpc
            fi.pool_tokens = frozenset(tokens)
            fi.lock_reach = frozenset(locks)
            fi.covered = f["fires_any"]

        # propagate reaches_* / pool_tokens / lock_reach up the graph
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                for tgt in fi.callees:
                    g = self.funcs[tgt]
                    if g.reaches_retry and not fi.reaches_retry:
                        fi.reaches_retry = True
                        changed = True
                    if g.reaches_rpc and not fi.reaches_rpc:
                        fi.reaches_rpc = True
                        changed = True
                    if not g.pool_tokens <= fi.pool_tokens:
                        fi.pool_tokens = fi.pool_tokens | g.pool_tokens
                        changed = True
                    if not g.lock_reach <= fi.lock_reach:
                        fi.lock_reach = fi.lock_reach | g.lock_reach
                        changed = True

        # holds-across-yield for context-manager functions
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                f = fi.summary
                if not f["is_acm"]:
                    continue
                held: set = set()
                at_yield: set = set()
                for ev in f["events"]:
                    k = ev["k"]
                    if k == "enter" and ev.get("callee"):
                        held |= self._direct_hold(fi, ev)
                    elif k == "await" and ev.get("callee"):
                        tok = _pool_token(ev["callee"], f["cls"])
                        if tok and "DTPU008" not in set(ev.get("noqa", ())):
                            held.add(("pool", tok))
                    elif k == "yield":
                        at_yield |= held
                if at_yield != set(fi.holds):
                    fi.holds = frozenset(at_yield)
                    changed = True

        # fault coverage: covered if self fires, or every caller covered
        changed = True
        while changed:
            changed = False
            for fi in self.funcs.values():
                if fi.covered or not fi.callers:
                    continue
                if all(self.funcs[c].covered for c in fi.callers):
                    fi.covered = True
                    changed = True

    def _direct_hold(self, fi: FuncInfo, ev: dict) -> set:
        """Resource tokens a with-item context installs, including
        those an asynccontextmanager holds across its yield."""
        callee = ev["callee"]
        final = callee.rsplit(".", 1)[-1]
        held: set = set()
        noqa = set(ev.get("noqa", ()))
        if "DTPU008" in noqa:
            return held
        if final == "transaction":
            held.add(("tx", callee))
        elif final in CLAIM_NAMES:
            held.add(("claim", ev.get("arg0") or callee))
        elif final in BUCKET_HOLD_NAMES:
            held.add(("bucket", callee))
        elif final in SLOT_HOLD_NAMES:
            held.add(("slot", callee))
        for tgt in self.resolve(fi.path, fi.summary["cls"], callee):
            g = self.funcs[tgt]
            if g.summary["is_acm"]:
                held |= set(g.holds)
        return held

    # -- convenience for rules --

    def functions(self) -> Iterable[FuncInfo]:
        return self.funcs.values()

    def callee_facts(self, fi: FuncInfo, callee: str) -> list["FuncInfo"]:
        return [
            self.funcs[k]
            for k in self.resolve(fi.path, fi.summary["cls"], callee)
        ]


# ---------------------------------------------------------------------------
# entry point + caching
# ---------------------------------------------------------------------------


def _glob_many(root: Path, globs: Sequence[str]) -> list[str]:
    rels: set = set()
    for g in globs:
        rels.update(p.relative_to(root).as_posix() for p in root.glob(g))
    return sorted(rels)


def report_paths(root: Path) -> set:
    from tools.dtpu_lint.core import glob_match

    out = set()
    for rel in _glob_many(root, REPORT_GLOBS):
        if not any(glob_match(rel, g) for g in REPORT_EXCLUDE):
            out.add(rel)
    return out


def _load_cache(path: Path, section: str = "files") -> dict:
    try:
        data = json.loads(path.read_text())
        if data.get("version") == analyzer_version():
            return data.get(section, {})
    except (OSError, ValueError):
        pass
    return {}


def _store_cache(path: Path, files: dict, section: str = "files") -> None:
    """Write one section, preserving the others (the async event-stream
    summaries and the SPMD summaries share `.flowcache.json`; each
    get_*_flow call refreshes only its own section)."""
    try:
        data = json.loads(path.read_text())
        if data.get("version") != analyzer_version():
            data = {}
    except (OSError, ValueError):
        data = {}
    data["version"] = analyzer_version()
    data[section] = files
    try:
        path.write_text(json.dumps(data))
    except OSError:
        pass  # read-only checkout: cache is an optimization only


#: in-process memo: root -> (state-digest, ProjectFlow) — four rules
#: in one lint run (and repeated run_lint calls in one pytest session)
#: share a single analysis; one live state per root
_memo: dict = {}


def get_flow(
    root: Path, cache_path: Optional[Path] = CACHE_PATH
) -> ProjectFlow:
    root = Path(root).resolve()
    if cache_path is CACHE_PATH:
        from tools.dtpu_lint.core import REPO

        if root != Path(REPO).resolve():
            # fixture trees (tests) must not churn the shared cache
            cache_path = None
    rels = _glob_many(root, ANALYZED_GLOBS)
    sources: dict[str, bytes] = {}
    digests: dict[str, str] = {}
    for rel in rels:
        try:
            raw = (root / rel).read_bytes()
        except OSError:
            continue
        sources[rel] = raw
        digests[rel] = _sha1(raw)
    state = _sha1(
        json.dumps(sorted(digests.items())).encode()
        + analyzer_version().encode()
    )
    hit = _memo.get(str(root))
    if hit is not None and hit[0] == state:
        return hit[1]

    cached = _load_cache(cache_path) if cache_path else {}
    fresh: dict = {}
    summaries: list[dict] = []
    for rel, raw in sorted(sources.items()):
        d = digests[rel]
        hit = cached.get(d)
        if hit is not None and hit.get("path") == rel:
            summaries.append(hit)
            fresh[d] = hit
            continue
        try:
            summary = extract_summary(raw.decode("utf-8"), rel)
        except (SyntaxError, UnicodeDecodeError):
            continue  # DTPU000 reports unparseable files already
        summaries.append(summary)
        fresh[d] = summary
    if cache_path and fresh != cached:
        _store_cache(cache_path, fresh)

    flow = ProjectFlow(root, summaries)
    _memo[str(root)] = (state, flow)
    return flow


# ---------------------------------------------------------------------------
# SPMD sharding analysis (DTPU012-014 + shardcheck's static side)
# ---------------------------------------------------------------------------
#
# A second, independent index over the *traced* compute plane: where the
# async analysis above follows awaits and resource holds, this one
# follows mesh-axis names. The unit of interest is an "axis reference"
# — a collective's axis argument, a ``shard_map`` spec entry, a
# ``PartitionSpec`` element — and the core problem is that the library
# idiom never writes the axis literal at the use site:
#
#     def ring_attention(q, k, v, mesh, axis_name: str = "sp", ...):
#         local_fn = _make_ring_pallas(sp, axis_name, ...)   # param ref
#         ...
#             kb = jax.lax.ppermute(kb, axis_name, perm)      # closure ref
#
# so per-function summaries record axis references symbolically
# ({"t": "param", "fq": owner, "p": name}) and :class:`SpmdFlow` runs a
# small interprocedural fixpoint mapping every axis-carrying parameter
# to the set of string literals that can flow into it (defaults plus
# call-site literals, transitively through parameter-to-parameter
# passes). Summaries are cached in `.flowcache.json` under a separate
# "spmd" section, keyed by content hash like the async ones.

#: files indexed for SPMD analysis (the traced compute plane)
SPMD_GLOBS = (
    "dstack_tpu/parallel/**/*.py",
    "dstack_tpu/ops/**/*.py",
    "dstack_tpu/models/**/*.py",
    "dstack_tpu/serve/engine.py",
)

#: the file whose module-level ``AXES = (...)`` tuple is the project's
#: mesh-axis vocabulary
MESH_AXES_FILE = "dstack_tpu/parallel/mesh.py"

#: collective name -> positional index of its axis-name argument
COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "ppermute": 1,
    "all_to_all": 1,
    "psum_scatter": 1,
    "axis_index": 0,
}

#: names bindable to jax.sharding.PartitionSpec by import
_PSPEC_NAMES = frozenset({"PartitionSpec", "P"})

#: attribute accesses that yield static (host) values even on traced
#: arrays — branching on these is shape-dependent Python, not a trace
#: divergence
_STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "sharding"})
_STATIC_CALLS = frozenset({"len", "isinstance", "range", "type", "getattr", "hasattr"})


def axis_vocabulary_from_source(src: str) -> frozenset:
    """Mesh-axis names from a module-level ``AXES = ("dp", ...)``."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return frozenset()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "AXES":
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        return frozenset(
                            e.value
                            for e in node.value.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        )
    return frozenset()


def axis_vocabulary(root: Path) -> frozenset:
    """The project's declared mesh-axis vocabulary (empty when the
    mesh module is absent — fixture trees without one skip the vocab
    checks)."""
    try:
        src = (Path(root) / MESH_AXES_FILE).read_text()
    except OSError:
        return frozenset()
    return axis_vocabulary_from_source(src)


# -- axis-reference encoding (JSON-friendly) --
# {"t": "lit", "v": "tp"}          a string literal
# {"t": "param", "fq": q, "p": n}  parameter `n` of function `q` (same file)
# {"t": "none"}                    an explicit None spec entry
# {"t": "unk", "v": "<expr>"}      statically unresolvable


def _lit(v):
    return {"t": "lit", "v": v}


class _SpmdEnv:
    """Per-function lexical environment: params, string locals, spec
    locals, taint. Chained through ``parent`` for closures."""

    def __init__(self, qual, params, parent=None):
        self.qual = qual
        self.params = list(params)
        self.parent = parent
        self.str_locals: dict = {}
        self.spec_locals: dict = {}  # name -> [axisref, ...] (one P(...))
        self.list_locals: dict = {}  # name -> [axisref, ...] (spec lists)
        self.tainted: set = set(params)

    def resolve_name(self, name):
        env = self
        while env is not None:
            if name in env.str_locals:
                return _lit(env.str_locals[name])
            if name in env.params:
                return {"t": "param", "fq": env.qual, "p": name}
            env = env.parent
        return {"t": "unk", "v": name}

    def lookup_spec(self, name):
        env = self
        while env is not None:
            if name in env.spec_locals:
                return list(env.spec_locals[name])
            if name in env.list_locals:
                return list(env.list_locals[name])
            env = env.parent
        return None


def _names_used(node) -> set:
    """Names an expression *dynamically* depends on: attribute reads of
    static metadata (``x.shape``) and calls like ``len()`` don't count
    — branching on those is shape-specialization, not a per-shard
    divergence."""
    out: set = set()

    def walk(n):
        if isinstance(n, ast.Attribute):
            if n.attr in _STATIC_ATTRS:
                return
            walk(n.value)
            return
        if isinstance(n, ast.Call):
            fn = n.func
            if isinstance(fn, ast.Name) and fn.id in _STATIC_CALLS:
                return
            # receiver methods that read metadata: x.shape[...] handled
            # above; anything else descends normally
            for child in ast.iter_child_nodes(n):
                walk(child)
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
            return
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return out


class _SpmdExtractor:
    """Extracts one function's SPMD events, recursing into nested
    functions (each nested def gets its own entry, with the lexical
    chain threaded for closure resolution)."""

    def __init__(self, lines, imports, functions_out):
        self.lines = lines
        self.imports = imports  # name -> dotted module/symbol
        self.functions = functions_out

    # -- helpers --

    def _noqa(self, line):
        return _line_pragmas(self.lines, line)

    def _is_numpy(self, root):
        return self.imports.get(root) == "numpy"

    def _is_pspec(self, call: ast.Call):
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name not in _PSPEC_NAMES:
            return False
        if isinstance(f, ast.Name):
            bound = self.imports.get(name, name)
            return bound.rsplit(".", 1)[-1] in _PSPEC_NAMES or name == "P"
        return True  # jax.sharding.PartitionSpec(...)

    def _parse_pspec_axes(self, call: ast.Call, env) -> list:
        axes: list = []

        def add(node):
            if isinstance(node, ast.Constant):
                if isinstance(node.value, str):
                    axes.append(_lit(node.value))
                elif node.value is None:
                    axes.append({"t": "none"})
            elif isinstance(node, ast.Name):
                axes.append(env.resolve_name(node.id))
            elif isinstance(node, (ast.Tuple, ast.List)):
                for e in node.elts:
                    add(e)
            elif isinstance(node, ast.Starred):
                # P(*([None, None, "tp"] + pad)): collect the literals
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        axes.append(_lit(sub.value))
            else:
                axes.append({"t": "unk", "v": ast.unparse(node)[:40]})

        for a in call.args:
            add(a)
        return axes

    def _parse_spec_expr(self, node, env) -> Optional[list]:
        """A shard_map in_specs/out_specs expression → flat axisref
        list, or None when unresolvable."""
        if isinstance(node, ast.Call):
            if self._is_pspec(node):
                return self._parse_pspec_axes(node, env)
            # tuple(in_specs) / list(in_specs) over a tracked local
            f = node.func
            if (
                isinstance(f, ast.Name)
                and f.id in ("tuple", "list")
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
            ):
                return env.lookup_spec(node.args[0].id)
            return None
        if isinstance(node, (ast.Tuple, ast.List)):
            out: list = []
            for e in node.elts:
                sub = self._parse_spec_expr(e, env)
                if sub is None:
                    return None
                out.extend(sub)
            return out
        if isinstance(node, ast.BinOp):
            # [P(None, "tp", None)] * 2 and listA + listB spec builders
            if isinstance(node.op, ast.Mult):
                return self._parse_spec_expr(node.left, env)
            if isinstance(node.op, ast.Add):
                left = self._parse_spec_expr(node.left, env)
                right = self._parse_spec_expr(node.right, env)
                if left is None or right is None:
                    return None
                return left + right
            return None
        if isinstance(node, ast.Name):
            hit = env.lookup_spec(node.id)
            if hit is not None:
                return hit
            return None
        if isinstance(node, ast.Constant) and node.value is None:
            return [{"t": "none"}]
        return None

    def _axisval(self, node, env):
        """A call argument as an axis value for binding flow, or None
        when uninteresting."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return _lit(node.value)
        if isinstance(node, ast.Name):
            ref = env.resolve_name(node.id)
            if ref["t"] in ("lit", "param"):
                return ref
        return None

    # -- the walk --

    def extract_function(self, node, qual, cls, env_parent):
        args = node.args
        params = [
            a.arg
            for a in (*args.posonlyargs, *args.args, *args.kwonlyargs)
        ]
        defaults: dict = {}
        pos = [*args.posonlyargs, *args.args]
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            if isinstance(d, ast.Constant) and isinstance(d.value, str):
                defaults[a.arg] = d.value
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if (
                d is not None
                and isinstance(d, ast.Constant)
                and isinstance(d.value, str)
            ):
                defaults[a.arg] = d.value
        env = _SpmdEnv(qual, params, env_parent)
        fn = {
            "name": node.name,
            "qual": qual,
            "cls": cls,
            "line": node.lineno,
            "params": [a.arg for a in (*args.posonlyargs, *args.args)],
            "kwparams": [a.arg for a in args.kwonlyargs],
            "defaults": defaults,
            "collectives": [],
            "host_syncs": [],
            "tainted_branches": [],
            "shard_maps": [],
            "pspecs": [],
            "calls": [],
        }
        self.functions.append(fn)
        self._walk_body(node.body, fn, env, qual, cls, cond=False)
        return fn

    def _walk_body(self, body, fn, env, qual, cls, cond):
        after_tainted_return = False
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.extract_function(
                    stmt, f"{qual}.<locals>.{stmt.name}", cls, env
                )
                continue
            self._walk_stmt(
                stmt, fn, env, qual, cls, cond or after_tainted_return
            )
            if self._stmt_has_tainted_early_exit(stmt, env):
                after_tainted_return = True

    def _stmt_has_tainted_early_exit(self, stmt, env) -> bool:
        """A tainted ``if`` that returns/raises makes everything after
        it conditional on per-shard data."""
        if not isinstance(stmt, ast.If):
            return False
        if not (_names_used(stmt.test) & env.tainted):
            return False
        return any(
            isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
            for branch in (stmt.body, stmt.orelse)
            for s in branch
        )

    def _walk_stmt(self, stmt, fn, env, qual, cls, cond):
        if isinstance(stmt, (ast.If, ast.While)):
            tainted = bool(_names_used(stmt.test) & env.tainted)
            if tainted:
                fn["tainted_branches"].append(
                    {
                        "line": stmt.lineno,
                        "test": ast.unparse(stmt.test)[:60],
                        "noqa": self._noqa(stmt.lineno),
                    }
                )
            self._walk_expr(stmt.test, fn, env, cond)
            self._walk_body(
                stmt.body, fn, env, qual, cls, cond or tainted
            )
            self._walk_body(
                stmt.orelse, fn, env, qual, cls, cond or tainted
            )
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._walk_expr(stmt.iter, fn, env, cond)
            if _names_used(stmt.iter) & env.tainted:
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        env.tainted.add(n.id)
            self._walk_body(stmt.body, fn, env, qual, cls, cond)
            self._walk_body(stmt.orelse, fn, env, qual, cls, cond)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._walk_expr(item.context_expr, fn, env, cond)
            self._walk_body(stmt.body, fn, env, qual, cls, cond)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, fn, env, qual, cls, cond)
            for h in stmt.handlers:
                self._walk_body(h.body, fn, env, qual, cls, cond)
            self._walk_body(stmt.orelse, fn, env, qual, cls, cond)
            self._walk_body(stmt.finalbody, fn, env, qual, cls, cond)
            return
        if isinstance(stmt, ast.Assign):
            self._track_assign(stmt, env)
            self._walk_expr(stmt.value, fn, env, cond)
            return
        if isinstance(stmt, ast.AugAssign):
            # in_specs += [P(...)] extends a tracked spec list
            if (
                isinstance(stmt.target, ast.Name)
                and isinstance(stmt.op, ast.Add)
                and stmt.target.id in env.list_locals
            ):
                more = self._parse_spec_expr(stmt.value, env)
                if more is not None:
                    env.list_locals[stmt.target.id].extend(more)
                else:
                    del env.list_locals[stmt.target.id]
            if _names_used(stmt.value) & env.tainted and isinstance(
                stmt.target, ast.Name
            ):
                env.tainted.add(stmt.target.id)
            self._walk_expr(stmt.value, fn, env, cond)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._walk_expr(stmt.value, fn, env, cond)
            return
        if isinstance(stmt, ast.Expr):
            # in_specs.append(P(...))
            v = stmt.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "append"
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id in env.list_locals
                and len(v.args) == 1
            ):
                more = self._parse_spec_expr(v.args[0], env)
                if more is not None:
                    env.list_locals[v.func.value.id].extend(more)
                else:
                    del env.list_locals[v.func.value.id]
            self._walk_expr(stmt.value, fn, env, cond)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._walk_expr(child, fn, env, cond)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child, fn, env, qual, cls, cond)

    def _track_assign(self, stmt: ast.Assign, env):
        if len(stmt.targets) != 1 or not isinstance(stmt.targets[0], ast.Name):
            # tuple unpack from tainted rhs taints all targets
            if _names_used(stmt.value) & env.tainted:
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            env.tainted.add(n.id)
            return
        name = stmt.targets[0].id
        v = stmt.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            env.str_locals[name] = v.value
        elif isinstance(v, ast.Call) and self._is_pspec(v):
            env.spec_locals[name] = self._parse_pspec_axes(v, env)
        elif isinstance(v, (ast.Tuple, ast.List)):
            spec = self._parse_spec_expr(v, env)
            if spec is not None:
                env.list_locals[name] = spec
        elif (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Name)
            and v.func.id in ("tuple", "list")
            and len(v.args) == 1
            and isinstance(v.args[0], ast.Name)
        ):
            hit = env.lookup_spec(v.args[0].id)
            if hit is not None:
                env.list_locals[name] = list(hit)
        if _names_used(v) & env.tainted:
            env.tainted.add(name)

    def _walk_expr(self, node, fn, env, cond):
        if isinstance(node, ast.IfExp):
            tainted = bool(_names_used(node.test) & env.tainted)
            self._walk_expr(node.test, fn, env, cond)
            self._walk_expr(node.body, fn, env, cond or tainted)
            self._walk_expr(node.orelse, fn, env, cond or tainted)
            return
        if isinstance(node, (ast.Lambda,)):
            return  # opaque; shard_map bodies are named functions here
        if isinstance(node, ast.Call):
            self._record_call(node, fn, env, cond)
            if not isinstance(node.func, (ast.Name, ast.Attribute)):
                # shard_map(...)(q, k, v): the wrap call lives in .func
                self._walk_expr(node.func, fn, env, cond)
            for a in node.args:
                if isinstance(a, ast.Starred):
                    self._walk_expr(a.value, fn, env, cond)
                else:
                    self._walk_expr(a, fn, env, cond)
            for kw in node.keywords:
                self._walk_expr(kw.value, fn, env, cond)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword)):
                self._walk_expr(
                    child.value if isinstance(child, ast.keyword) else child,
                    fn,
                    env,
                    cond,
                )

    def _record_call(self, call: ast.Call, fn, env, cond):
        callee = callee_str(call.func)
        f = call.func
        final = None
        if isinstance(f, ast.Attribute):
            final = f.attr
        elif isinstance(f, ast.Name):
            final = f.id
        line = call.lineno

        # host syncs (DTPU013's raw material)
        if (
            isinstance(f, ast.Attribute)
            and final == "item"
            and not call.args
            and not call.keywords
        ):
            fn["host_syncs"].append(
                {"line": line, "what": ".item()", "noqa": self._noqa(line)}
            )
        elif isinstance(f, ast.Attribute) and final == "block_until_ready":
            fn["host_syncs"].append(
                {
                    "line": line,
                    "what": ".block_until_ready()",
                    "noqa": self._noqa(line),
                }
            )
        elif final == "device_get" and (
            (isinstance(f, ast.Name) and self.imports.get(final) == "jax.device_get")
            or (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and self.imports.get(f.value.id, f.value.id) == "jax"
            )
        ):
            fn["host_syncs"].append(
                {
                    "line": line,
                    "what": "jax.device_get()",
                    "noqa": self._noqa(line),
                }
            )
        elif final == "asarray" and isinstance(f, ast.Attribute) and isinstance(
            f.value, ast.Name
        ) and self._is_numpy(f.value.id):
            fn["host_syncs"].append(
                {
                    "line": line,
                    "what": "np.asarray()",
                    "noqa": self._noqa(line),
                }
            )
        elif final in ("pure_callback", "io_callback") or (
            final == "callback"
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, (ast.Attribute, ast.Name))
            and (callee or "").split(".")[-2:-1] == ["debug"]
        ):
            fn["host_syncs"].append(
                {
                    "line": line,
                    "what": f"host callback {final}()",
                    "noqa": self._noqa(line),
                }
            )

        # collectives
        if final in COLLECTIVES and (
            callee is None
            or callee in (final, f"lax.{final}", f"jax.lax.{final}")
            or callee.endswith(f".lax.{final}")
        ):
            axis_pos = COLLECTIVES[final]
            axis_node = None
            if len(call.args) > axis_pos:
                axis_node = call.args[axis_pos]
            else:
                for kw in call.keywords:
                    if kw.arg in ("axis_name", "axis"):
                        axis_node = kw.value
            if axis_node is None:
                ref = {"t": "unk", "v": "<missing axis>"}
            elif isinstance(axis_node, ast.Constant) and isinstance(
                axis_node.value, str
            ):
                ref = _lit(axis_node.value)
            elif isinstance(axis_node, ast.Name):
                ref = env.resolve_name(axis_node.id)
            elif isinstance(axis_node, (ast.Tuple, ast.List)):
                refs = []
                for e in axis_node.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        refs.append(_lit(e.value))
                    elif isinstance(e, ast.Name):
                        refs.append(env.resolve_name(e.id))
                    else:
                        refs.append({"t": "unk", "v": ast.unparse(e)[:40]})
                for r in refs:
                    fn["collectives"].append(
                        {
                            "line": line,
                            "fn": final,
                            "axis": r,
                            "cond": cond,
                            "noqa": self._noqa(line),
                        }
                    )
                return
            else:
                ref = {"t": "unk", "v": ast.unparse(axis_node)[:40]}
            fn["collectives"].append(
                {
                    "line": line,
                    "fn": final,
                    "axis": ref,
                    "cond": cond,
                    "noqa": self._noqa(line),
                }
            )

        # shard_map(...) wrap sites
        if final == "shard_map" and (call.keywords or len(call.args) > 1):
            body_name = (
                call.args[0].id
                if call.args and isinstance(call.args[0], ast.Name)
                else None
            )
            in_axes = out_axes = None
            axis_names: list = []
            unknown_specs = False
            for kw in call.keywords:
                if kw.arg == "in_specs":
                    in_axes = self._parse_spec_expr(kw.value, env)
                    unknown_specs |= in_axes is None
                elif kw.arg == "out_specs":
                    out_axes = self._parse_spec_expr(kw.value, env)
                    unknown_specs |= out_axes is None
                elif kw.arg == "axis_names" and isinstance(
                    kw.value, (ast.Set, ast.Tuple, ast.List)
                ):
                    for e in kw.value.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                            e.value, str
                        ):
                            axis_names.append(_lit(e.value))
                        elif isinstance(e, ast.Name):
                            axis_names.append(env.resolve_name(e.id))
            fn["shard_maps"].append(
                {
                    "line": line,
                    "body": body_name,
                    "in_axes": in_axes or [],
                    "out_axes": out_axes or [],
                    "axis_names": axis_names,
                    "unknown_specs": unknown_specs,
                    "noqa": self._noqa(line),
                }
            )

        # bare PartitionSpec construction (vocabulary check)
        if self._is_pspec(call):
            axes = self._parse_pspec_axes(call, env)
            if axes:
                fn["pspecs"].append(
                    {"line": line, "axes": axes, "noqa": self._noqa(line)}
                )

        # calls (graph edges + axis-binding flow)
        if callee is not None:
            a = [self._axisval(x, env) for x in call.args]
            k = {
                kw.arg: self._axisval(kw.value, env)
                for kw in call.keywords
                if kw.arg is not None
            }
            k = {n: v for n, v in k.items() if v is not None}
            fn["calls"].append(
                {"line": line, "callee": callee, "a": a, "k": k}
            )


def extract_spmd_summary(src: str, relpath: str) -> dict:
    """Pure per-file SPMD pass (cached by content hash, "spmd" section)."""
    tree = ast.parse(src, filename=relpath)
    lines = src.splitlines()
    imports: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                imports[a.asname or a.name] = f"{node.module}.{a.name}"

    functions: list = []
    ex = _SpmdExtractor(lines, imports, functions)

    # module-level PartitionSpec literals (e.g. cache spec constants)
    mod_fn = {
        "name": "<module>",
        "qual": "<module>",
        "cls": None,
        "line": 1,
        "params": [],
        "kwparams": [],
        "defaults": {},
        "collectives": [],
        "host_syncs": [],
        "tainted_branches": [],
        "shard_maps": [],
        "pspecs": [],
        "calls": [],
    }
    mod_env = _SpmdEnv("<module>", [])
    mod_env.tainted = set()  # nothing is per-shard at module level

    def _walk_top(body, cls, prefix):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ex.extract_function(node, f"{prefix}{node.name}", cls, None)
            elif isinstance(node, ast.ClassDef):
                _walk_top(node.body, node.name, f"{node.name}.")
            elif isinstance(node, (ast.Assign, ast.Expr)):
                ex._walk_stmt(
                    node, mod_fn, mod_env, "<module>", None, cond=False
                )

    _walk_top(tree.body, None, "")
    if any(
        mod_fn[k]
        for k in ("collectives", "pspecs", "shard_maps", "host_syncs", "calls")
    ):
        functions.append(mod_fn)
    return {"path": relpath, "imports": imports, "functions": functions}


class SpmdFlow:
    """Resolved SPMD index: axis-literal bindings per parameter, the
    shard_map body set, reachability, per-body transitive collective
    axes. Rules DTPU012-014 and shardcheck's static checks read this."""

    def __init__(self, root: Path, summaries: list, vocab: frozenset):
        self.root = root
        self.vocab = vocab
        self.summaries = summaries
        self.funcs: dict = {}  # key "path::qual" -> fn summary dict
        self.paths: dict = {}  # key -> path
        self.by_name: dict = {}
        self.module_symbols: dict = {}
        self.imports: dict = {}
        for s in summaries:
            self.imports[s["path"]] = s.get("imports", {})
            for f in s["functions"]:
                key = f"{s['path']}::{f['qual']}"
                self.funcs[key] = f
                self.paths[key] = s["path"]
                self.by_name.setdefault(f["name"], []).append(key)
                if f["cls"] is None and ".<locals>." not in f["qual"]:
                    self.module_symbols[(s["path"], f["name"])] = key
        self._resolve_cache: dict = {}
        self.callees: dict = {k: set() for k in self.funcs}
        self.callers: dict = {k: set() for k in self.funcs}
        self._build_graph()
        self.bindings: dict = {}  # (path, qual, param) -> {lit: (path, line)}
        self._bind_fixpoint()
        self.bodies: set = self._find_bodies()
        self.traced: set = self._traced_set()

    # -- resolution --

    def _module_for(self, dotted: str):
        rel = dotted.replace(".", "/")
        for cand in (f"{rel}.py", f"{rel}/__init__.py"):
            if any(s["path"] == cand for s in self.summaries):
                return cand
        return None

    def resolve(self, path: str, qual: str, callee: str) -> list:
        return self.resolve_ex(path, qual, callee)[0]

    def resolve_ex(self, path: str, qual: str, callee: str) -> tuple:
        """→ (candidate keys, strict). ``strict`` is False when the
        binding came from the by-name union fallback — a conservative
        over-approximation good for reachability facts but too loose
        for the axis-coverage check."""
        ck = (path, qual, callee)
        if ck in self._resolve_cache:
            return self._resolve_cache[ck]
        parts = callee.split(".")
        final = parts[-1].split("()")[0]
        out: list = []
        strict = True
        if len(parts) == 1:
            # nested def in the enclosing chain, innermost first
            q = qual
            while True:
                cand = f"{path}::{q}.<locals>.{final}"
                if cand in self.funcs:
                    out = [cand]
                    break
                if ".<locals>." not in q:
                    break
                q = q.rsplit(".<locals>.", 1)[0]
            if not out:
                key = self.module_symbols.get((path, final))
                if key:
                    out = [key]
            if not out:
                imp = self.imports.get(path, {}).get(final)
                if imp and "." in imp:
                    mod, name = imp.rsplit(".", 1)
                    mpath = self._module_for(mod)
                    if mpath:
                        k = self.module_symbols.get((mpath, name))
                        if k:
                            out = [k]
            if not out:
                out = self._union(final)
                strict = False
        elif parts[0] == "self":
            out = self._union(final)
            strict = False
        else:
            # dotted: resolve the root through import aliases. An
            # external module (imported but not indexed — jnp, np,
            # torch) or an unknown receiver must NOT fall back to the
            # name union: `jnp.stack` resolving to every local `stack`
            # helper would drag host-side code into the traced set.
            root_name = parts[0].split("()")[0]
            imp = self.imports.get(path, {}).get(root_name)
            if imp and len(parts) == 2:
                mpath = self._module_for(imp)
                if mpath:
                    k = self.module_symbols.get((mpath, final))
                    out = [k] if k else []
        res = (out, strict)
        self._resolve_cache[ck] = res
        return res

    def _union(self, name: str) -> list:
        if name in _UNION_BLOCKLIST or name in (
            "jit", "vmap", "scan", "partial", "checkpoint", "forward",
        ):
            return []
        return list(self.by_name.get(name, []))

    def _build_graph(self) -> None:
        self.callees_strict: dict = {k: set() for k in self.funcs}
        for key, f in self.funcs.items():
            path = self.paths[key]
            for call in f["calls"]:
                tgts, strict = self.resolve_ex(path, f["qual"], call["callee"])
                for tgt in tgts:
                    self.callees[key].add(tgt)
                    self.callers[tgt].add(key)
                    if strict:
                        self.callees_strict[key].add(tgt)
            # closure edge: a nested def runs under its enclosing fn
            if ".<locals>." in f["qual"]:
                outer = f"{path}::{f['qual'].rsplit('.<locals>.', 1)[0]}"
                if outer in self.funcs:
                    self.callees[outer].add(key)
                    self.callers[key].add(outer)

    # -- axis-literal binding fixpoint --

    def _bind_key(self, path, qual, param):
        return (path, qual, param)

    def _bind_fixpoint(self) -> None:
        binds = self.bindings
        for key, f in self.funcs.items():
            path = self.paths[key]
            for p, lit in f["defaults"].items():
                binds.setdefault(self._bind_key(path, f["qual"], p), {})[
                    lit
                ] = (path, f["line"])
        changed = True
        while changed:
            changed = False
            for key, f in self.funcs.items():
                path = self.paths[key]
                for call in f["calls"]:
                    tgts = self.resolve(path, f["qual"], call["callee"])
                    for tgt in tgts:
                        g = self.funcs[tgt]
                        gpath = self.paths[tgt]
                        pairs = []
                        for i, v in enumerate(call["a"]):
                            if v is not None and i < len(g["params"]):
                                pairs.append((g["params"][i], v))
                        for n, v in call["k"].items():
                            if n in g["params"] or n in g["kwparams"]:
                                pairs.append((n, v))
                        for pname, v in pairs:
                            bk = self._bind_key(gpath, g["qual"], pname)
                            cur = binds.setdefault(bk, {})
                            if v["t"] == "lit":
                                if v["v"] not in cur:
                                    cur[v["v"]] = (path, call["line"])
                                    changed = True
                            elif v["t"] == "param":
                                src = binds.get(
                                    self._bind_key(path, v["fq"], v["p"]), {}
                                )
                                for lit, origin in src.items():
                                    if lit not in cur:
                                        cur[lit] = origin
                                        changed = True

    def resolve_axis(self, path: str, ref: dict) -> Optional[dict]:
        """Axis reference → {literal: origin} map; None = unresolvable."""
        if ref["t"] == "lit":
            return {ref["v"]: (path, 0)}
        if ref["t"] == "param":
            hit = self.bindings.get(
                self._bind_key(path, ref["fq"], ref["p"]), {}
            )
            return hit or None
        if ref["t"] == "none":
            return {}
        return None

    # -- traced-set computation --

    def _find_bodies(self) -> set:
        """All functions a shard_map site may wrap. A body named by a
        plain variable (``local_fn = _make_ring(...)``) resolves by
        name union — every same-named candidate is a possible body
        (the ring/ulysses impl dispatch really does pick between
        them), so sites carry the full candidate list."""
        bodies: set = set()
        self.body_sites: list = []  # (wrapping-fn key, sm event, [body keys])
        for key, f in self.funcs.items():
            path = self.paths[key]
            for sm in f["shard_maps"]:
                cands: list = []
                if sm["body"]:
                    cands = self.resolve(path, f["qual"], sm["body"])
                bodies.update(cands)
                self.body_sites.append((key, sm, cands))
        return bodies

    def _descendants(self, seeds: set) -> set:
        seen = set(seeds)
        frontier = list(seeds)
        while frontier:
            k = frontier.pop()
            for tgt in self.callees.get(k, ()):
                if tgt not in seen:
                    seen.add(tgt)
                    frontier.append(tgt)
        return seen

    def _traced_set(self) -> set:
        seeds = set(self.bodies)
        for key, f in self.funcs.items():
            if f["collectives"]:
                seeds.add(key)
        return self._descendants(seeds)

    def transitive_collective_axes(self, body_key: str) -> list:
        """Collective axis refs attributable to ``body_key`` →
        [(owner_key, event)]. Follows strict (non-union) call edges
        plus the body's lexical sibling closures — a custom_vjp's
        fwd/bwd live beside the shard_map body inside the same
        factory and run under the same mapping, but no syntactic call
        connects them. Union edges are excluded: they would attribute
        another wrapper's collectives to this body (e.g. the pipeline
        body union-reaching attention code it never traces)."""
        seeds = {body_key}
        qual = self.funcs[body_key]["qual"]
        path = self.paths[body_key]
        if ".<locals>." in qual:
            prefix = qual.rsplit(".<locals>.", 1)[0] + ".<locals>."
            for k, f in self.funcs.items():
                if self.paths[k] == path and f["qual"].startswith(prefix):
                    seeds.add(k)
        seen = set(seeds)
        frontier = list(seeds)
        while frontier:
            k = frontier.pop()
            for tgt in self.callees_strict.get(k, ()):
                if tgt not in seen:
                    seen.add(tgt)
                    frontier.append(tgt)
        out: list = []
        for k in sorted(seen):
            for ev in self.funcs[k]["collectives"]:
                out.append((k, ev))
        return out

    def functions_items(self):
        return self.funcs.items()


_spmd_memo: dict = {}


def get_spmd_flow(
    root: Path, cache_path: Optional[Path] = CACHE_PATH
) -> SpmdFlow:
    root = Path(root).resolve()
    if cache_path is CACHE_PATH:
        from tools.dtpu_lint.core import REPO

        if root != Path(REPO).resolve():
            cache_path = None  # fixture trees must not churn the cache
    rels = _glob_many(root, SPMD_GLOBS)
    sources: dict = {}
    digests: dict = {}
    for rel in rels:
        try:
            raw = (root / rel).read_bytes()
        except OSError:
            continue
        sources[rel] = raw
        digests[rel] = _sha1(raw)
    state = _sha1(
        json.dumps(sorted(digests.items())).encode()
        + analyzer_version().encode()
    )
    hit = _spmd_memo.get(str(root))
    if hit is not None and hit[0] == state:
        return hit[1]

    cached = _load_cache(cache_path, "spmd") if cache_path else {}
    fresh: dict = {}
    summaries: list = []
    for rel, raw in sorted(sources.items()):
        d = digests[rel]
        prev = cached.get(d)
        if prev is not None and prev.get("path") == rel:
            summaries.append(prev)
            fresh[d] = prev
            continue
        try:
            summary = extract_spmd_summary(raw.decode("utf-8"), rel)
        except (SyntaxError, UnicodeDecodeError):
            continue  # DTPU000 reports unparseable files already
        summaries.append(summary)
        fresh[d] = summary
    if cache_path and fresh != cached:
        _store_cache(cache_path, fresh, "spmd")

    flow = SpmdFlow(root, summaries, axis_vocabulary(root))
    _spmd_memo[str(root)] = (state, flow)
    return flow
