"""One-command TPU evidence capture for a tunnel window.

The axon tunnel to the real chip comes and goes; when it is up, this
script captures EVERYTHING this round needs in one go and appends each
result to ``BENCH_TPU_r05_evidence.json``:

1. the full headline bench (train MFU + serve decode + prefix TTFT pair)
2. Llama-3-8B int8 + int8-KV serving decode/TTFT (BASELINE.md's named
   target model — random-init weights; throughput/latency are
   weight-value-independent)
3. the serving latency-under-load curve (concurrency × turbo cells)
4. the flash-attention block sweep (tools/mfu_sweep.py)
5. the roofline lever sweep (int8 Adam / batch / grad-accum variants)

Each phase is independently fault-isolated (subprocess + timeout): a
tunnel drop mid-phase records the failure note and moves on, so a
partial window still yields evidence.

Usage: ``python tools/tpu_capture.py [--quick] [--phases 1,2,3,4]``
"""

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
EVIDENCE = REPO / "BENCH_TPU_r05_evidence.json"
# phase N (1-based) = PHASES[N-1]; tpu_watcher.py imports both names
PHASES = (
    "headline_bench",
    "serve_8b_int8",
    "latency_under_load",
    "mfu_sweep",
    "roofline_levers",
    # re-run of the headline bench: phase 1's 08:31Z capture predates
    # the device-resident decode state and pipelined turbo chaining, so
    # its embedded serve numbers undersell the current engine
    "headline_refresh",
    # ragged pallas decode kernel vs the masked einsum (ops/flash_decode)
    "decode_kernel_ab",
)


def _now() -> str:
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%MZ")


def _append(entry: dict) -> None:
    data = {"runs": []}
    if EVIDENCE.exists():
        try:
            data = json.loads(EVIDENCE.read_text())
        except ValueError:
            pass
    data.setdefault("runs", []).append(entry)
    EVIDENCE.write_text(json.dumps(data, indent=1))
    print(f"recorded -> {EVIDENCE.name}: {entry.get('phase')}", flush=True)


def _run(phase: str, cmd: list, timeout: int) -> dict:
    """Run one phase, append its evidence entry, and return it (callers
    can check for 'error' / alias a fresh result)."""
    print(f"=== {phase}: {' '.join(cmd)}", flush=True)
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, timeout=timeout, capture_output=True, text=True
        )
    except subprocess.TimeoutExpired:
        entry = {
            "phase": phase, "captured": _now(),
            "error": f"timeout {timeout}s",
        }
        _append(entry)
        return entry
    lines = [
        ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
    ]
    if proc.returncode != 0 or not lines:
        entry = {
            "phase": phase, "captured": _now(),
            "error": (proc.stderr or proc.stdout).strip()[-400:],
        }
        _append(entry)
        return entry
    results = []
    for ln in lines:
        try:
            results.append(json.loads(ln))
        except ValueError:
            pass
    entry = {
        "phase": phase,
        "captured": _now(),
        "wall_s": round(time.time() - t0, 1),
        "results": results,
    }
    if cpu_fallback(results):
        entry["error"] = "cpu fallback (tunnel down mid-window)"
    _append(entry)
    return entry


def cpu_fallback(results: list) -> bool:
    """True when a tool smoke-fell-back to CPU and exited 0 — that is
    NOT captured TPU evidence; the entry gets marked so the
    window-watcher retries the phase instead of counting it done.
    Structured flags first (fallback/platform/backend emitted by the
    tools — serve bench nests its backend under ``extra``), then a
    case-insensitive note check as the belt for tools predating the
    flags."""
    structured = any(
        r.get("fallback") is True or r.get("platform") == "cpu"
        or r.get("backend") == "cpu"
        or (isinstance(r.get("extra"), dict)
            and r["extra"].get("backend") == "cpu")
        or (isinstance(r.get("metric"), str) and ",cpu]" in r["metric"])
        for r in results
    )
    noted = any(
        "tpu unreachable" in str(r.get("note", "")).lower()
        or "cpu fallback" in str(r.get("note", "")).lower()
        for r in results
    )
    return structured or noted


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--phases", default="1,2,3,4,5,6,7")
    args = p.parse_args()
    phases = {int(x) for x in args.phases.split(",")}
    py = sys.executable
    env_note = os.environ.get("JAX_PLATFORMS", "(default)")
    print(f"capture start {_now()} JAX_PLATFORMS={env_note}", flush=True)

    headline_entry = None
    if 1 in phases:
        headline_entry = _run(
            "headline_bench",
            [py, "bench.py"] + (["--quick"] if args.quick else []),
            timeout=2700)
    if 2 in phases:
        # 8B fits 16 GiB only with int8 weights + int8 KV. batch 8 /
        # seq 2048 sized for (8.03 GB weights + cache) headroom.
        _run("serve_8b_int8",
             [py, "-m", "dstack_tpu.serve.bench",
              "--model", "llama-3-8b", "--quantize", "int8",
              "--kv-quant", "int8", "--batch", "8",
              "--max-seq", "2048", "--prompt-len", "512",
              "--gen-len", "64" if args.quick else "128",
              "--turbo-steps", "32", "--turbo-depth", "4"],
             timeout=3000)
    if 3 in phases:
        _run("latency_under_load",
             [py, "tools/latency_bench.py", "--model", "llama-3.2-1b",
              "--batch", "16", "--max-seq", "1024",
              "--prompt-len", "256", "--gen-len", "64",
              "--concurrency", "1", "4", "16", "32",
              "--turbo", "1", "8", "32", "128"],
             timeout=3600)
    if 4 in phases:
        _run("mfu_sweep",
             [py, "tools/mfu_sweep.py"],
             timeout=2700)
    if 5 in phases:
        # roofline levers (verdict r4 #2): int8 Adam state, lifted
        # batch, grad accumulation — one JSON line per variant
        _run("roofline_levers",
             [py, "tools/roofline_levers.py"],
             timeout=5400)
    if 6 in phases:
        # headline_refresh exists because a PREVIOUS window's phase-1
        # entry predates engine improvements; when phase 1 just ran in
        # THIS window the result is already fresh — alias it instead of
        # burning another ~45 min of scarce tunnel time on a rerun
        if headline_entry is not None and "error" not in headline_entry:
            _append({
                **headline_entry,
                "phase": "headline_refresh",
                "note": "alias of headline_bench captured this window",
            })
        else:
            _run("headline_refresh",
                 [py, "bench.py"] + (["--quick"] if args.quick else []),
                 timeout=2700)
    if 7 in phases:
        _run("decode_kernel_ab",
             [py, "tools/decode_kernel_ab.py"],
             timeout=3600)
    print(f"capture done {_now()}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
