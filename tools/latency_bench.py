"""Serving latency under load: TTFT / inter-token latency vs concurrency.

Round-3 verdict: 1408 tok/s aggregate decode said nothing about what a
single request experiences when it arrives mid-macro-step. This harness
drives the FULL serving stack (OpenAI HTTP app → Scheduler → engine)
with C concurrent streaming clients and reports per-request TTFT and
inter-token gaps, for turbo K ∈ {1, 8, 32, 128} with the adaptive-K
policy on (default) or pinned off (``--no-adaptive`` sets
``turbo_quiet_s=0`` and pre-ramps K to the max so the old fixed-K
behavior is measurable).

Run on the target TPU for real numbers::

    python tools/latency_bench.py --model llama-3.2-1b --batch 16 \
        --concurrency 1 4 16 32 --turbo 1 8 32 128

CPU runs (llama-tiny) are smoke tests of the harness itself.
Prints one JSON line per (concurrency, turbo) cell.
"""

import argparse
import asyncio
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _pct(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


async def _one_client(client, prompt: str, gen_len: int) -> dict:
    """One streaming chat request → timing record."""
    t0 = time.perf_counter()
    times = []
    async with client.post(
        "/v1/chat/completions",
        json={
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": gen_len,
            "stream": True,
            "temperature": 0,
        },
    ) as resp:
        assert resp.status == 200, await resp.text()
        async for raw in resp.content:
            line = raw.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            obj = json.loads(line[len("data: "):])
            delta = obj.get("choices", [{}])[0].get("delta", {})
            if delta.get("content"):
                times.append(time.perf_counter())
    if not times:
        return {"ttft_ms": None, "itl_ms": [], "tokens": 0}
    return {
        "ttft_ms": (times[0] - t0) * 1e3,
        # chunk gaps approximate ITL (a chunk may carry >1 token under
        # turbo; that IS the latency a client sees)
        "itl_ms": [
            (b - a) * 1e3 for a, b in zip(times, times[1:])
        ],
        "tokens": len(times),
    }


async def bench_cell(
    make_engine, tokenizer, concurrency: int, turbo: int,
    n_requests: int, prompt_len: int, gen_len: int, adaptive: bool,
) -> dict:
    from aiohttp.test_utils import TestClient, TestServer

    from dstack_tpu.serve.openai_server import build_app

    engine = make_engine(turbo, adaptive)
    app = build_app(engine, tokenizer, "bench")
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        base = "lorem ipsum dolor sit amet " * (prompt_len // 27 + 1)
        # warmup: compile prefill chunks + every decode_loop K-variant
        # the adaptive ramp can reach, outside the timed window
        await _one_client(client, base[:prompt_len] + "req9", gen_len)
        await _one_client(client, base[:prompt_len] + "req8", gen_len)
        t0 = time.perf_counter()
        sem = asyncio.Semaphore(concurrency)
        results = []

        async def worker(i: int):
            async with sem:
                # distinct prompt tails avoid prefix-cache hits
                # flattering TTFT
                # fixed-width suffix: constant token length across
                # requests, so the last prefill chunk's (len, start)
                # variant compiles once in warmup, not per request
                r = await _one_client(
                    client, f"{base[:prompt_len]}req{i % 10}", gen_len
                )
                results.append(r)

        await asyncio.gather(*(worker(i) for i in range(n_requests)))
        wall = time.perf_counter() - t0
    finally:
        await client.close()
    ttfts = [r["ttft_ms"] for r in results if r["ttft_ms"] is not None]
    itls = [g for r in results for g in r["itl_ms"]]
    toks = sum(r["tokens"] for r in results)
    return {
        "metric": "serve_latency_under_load",
        "concurrency": concurrency,
        "turbo": turbo,
        "adaptive_k": adaptive,
        "requests": n_requests,
        "ttft_ms_p50": round(_pct(ttfts, 0.5), 1) if ttfts else None,
        "ttft_ms_p99": round(_pct(ttfts, 0.99), 1) if ttfts else None,
        "itl_ms_p50": round(_pct(itls, 0.5), 1) if itls else None,
        "itl_ms_p99": round(_pct(itls, 0.99), 1) if itls else None,
        "throughput_tok_s": round(toks / wall, 1),
        "wall_s": round(wall, 1),
    }


async def main_async(args) -> int:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from dstack_tpu.models import llama
    from dstack_tpu.serve.engine import InferenceEngine
    from dstack_tpu.serve.tokenizer import ByteTokenizer

    config = llama.CONFIGS[args.model]
    params = llama.init_params(config, jax.random.key(0))
    if args.quantize == "int8":
        from dstack_tpu.models.quant import quantize_tree

        params = quantize_tree(params, config)

    def make_engine(turbo, adaptive):
        eng = InferenceEngine(
            config, params, max_batch=args.batch, max_seq=args.max_seq,
            spec_draft=0, turbo_steps=turbo, kv_quant=args.kv_quant,
            turbo_quiet_s=0.5 if adaptive else 0.0,
            # near-identical bench prompts would prefix-hit and skip
            # prefill — this bench measures the COLD path
            prefix_cache=False,
        )
        if not adaptive:
            eng._turbo_k = max(turbo, 1)  # pre-ramped: fixed-K baseline
            eng.waiting_requests = 0
            # keep it pinned: quiet window 0 and no snap-back floor
            eng._adaptive_turbo_cap = lambda: max(turbo, 1)  # type: ignore
        return eng

    tokenizer = ByteTokenizer()
    for concurrency in args.concurrency:
        for turbo in args.turbo:
            cell = await bench_cell(
                make_engine, tokenizer, concurrency, turbo,
                n_requests=args.requests or concurrency * 3,
                prompt_len=args.prompt_len, gen_len=args.gen_len,
                adaptive=not args.no_adaptive,
            )
            cell["model"] = args.model
            cell["backend"] = jax.default_backend()
            print(json.dumps(cell), flush=True)
    return 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama-tiny")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--max-seq", type=int, default=1024)
    p.add_argument("--prompt-len", type=int, default=256)
    p.add_argument("--gen-len", type=int, default=64)
    p.add_argument("--requests", type=int, default=0,
                   help="total requests per cell (default 3x concurrency)")
    p.add_argument("--concurrency", type=int, nargs="+", default=[1, 4])
    p.add_argument("--turbo", type=int, nargs="+", default=[1, 8])
    p.add_argument("--quantize", default=None, choices=["int8"])
    p.add_argument("--kv-quant", default=None, choices=["int8"])
    p.add_argument("--no-adaptive", action="store_true")
    p.add_argument("--platform", default=None)
    args = p.parse_args()
    return asyncio.run(main_async(args))


if __name__ == "__main__":
    sys.exit(main())
