"""Headline benchmark: train-step tokens/sec/chip on the flagship model.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

The reference publishes no framework perf numbers (BASELINE.md), so
``vs_baseline`` is hardware-normalized: measured model-FLOPs utilization
(MFU) divided by a 0.40 MFU target — the level a well-tuned production
JAX stack reaches on this class of model. >1.0 beats that bar.

Runs on whatever accelerator is visible (single TPU chip under the
driver); falls back to a tiny CPU measurement if no TPU, so the line is
always printed.
"""

import json
import os
import statistics
import subprocess
import sys
import time


def _tpu_reachable(timeout: float = 120.0) -> bool:
    """Shared subprocess probe (a broken axon tunnel HANGS device
    enumeration; the probe hangs → kill it → fall back to CPU with an
    honest note). One implementation: utils/tpu_probe."""
    from dstack_tpu.utils.tpu_probe import tpu_reachable

    return tpu_reachable(timeout=timeout)


def _wait_for_tpu(budget_s: float, probe_timeout: float = 120.0) -> dict:
    """Keep probing for the TPU until it answers or ``budget_s`` runs
    out. Tunnel outages are transient (rounds 2 and 3 both lost their
    driver-captured TPU number to a one-shot probe), so we retry for
    minutes — not attempts — before conceding to the CPU fallback.

    Returns ``{"ok": bool, "attempts": N, "waited_s": S}``.
    """
    t0 = time.monotonic()
    attempts = 0
    while True:
        attempts += 1
        if _tpu_reachable(timeout=probe_timeout):
            return {
                "ok": True,
                "attempts": attempts,
                "waited_s": round(time.monotonic() - t0, 1),
            }
        elapsed = time.monotonic() - t0
        if elapsed >= budget_s:
            return {
                "ok": False,
                "attempts": attempts,
                "waited_s": round(elapsed, 1),
            }
        # a failed probe already burned up to probe_timeout seconds;
        # short sleep between probes so a tunnel flap is caught quickly
        time.sleep(min(30.0, max(0.0, budget_s - elapsed)))


def train_bench(
    config=None,
    batch: int = 8,
    seq: int = 1024,
    steps: int = 20,
    peak_flops: float = 197e12,
    opt_bits: int = 32,
    grad_accum: int = 1,
    loss_impl: str = "fused",
) -> dict:
    """One parameterized train-step measurement (used by the headline
    bench AND tools/roofline_levers.py's lever sweep). ``batch`` is the
    TOTAL batch; with ``grad_accum > 1`` each microbatch is
    batch/grad_accum and one optimizer update covers the whole batch."""
    import jax
    import jax.numpy as jnp

    from dstack_tpu.models import llama
    from dstack_tpu.parallel.mesh import MeshConfig, make_mesh
    from dstack_tpu.train.step import (
        default_optimizer,
        flops_per_token,
        make_train_step,
        sharded_init,
    )

    config = config or llama.LLAMA_32_1B
    mesh = make_mesh(
        MeshConfig(dp=1, fsdp=1, sp=1, tp=1), devices=jax.devices()[:1]
    )
    opt = default_optimizer(lr=1e-4, opt_bits=opt_bits)
    state, _ = sharded_init(config, opt, mesh, seed=0)
    step_fn = make_train_step(
        config, opt, mesh, grad_accum=grad_accum, loss_impl=loss_impl
    )

    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0, config.vocab_size)
    data = {
        "tokens": tokens,
        "targets": jnp.roll(tokens, -1, axis=1),
        "mask": jnp.ones_like(tokens),
    }

    def sync(x):
        # device_get forces a real device->host round trip; under remote
        # (tunneled) platforms block_until_ready alone may not wait for
        # the computation.
        jax.block_until_ready(x)
        return float(jax.device_get(x))

    # warmup / compile
    state, m = step_fn(state, data)
    sync(m["loss"])
    state, m = step_fn(state, data)
    sync(m["loss"])

    # Steady-state timing: chain `inner` dependent steps between host
    # syncs so the per-sync host↔device round trip (large under the
    # tunneled single-chip driver) amortizes like it does in a real
    # training loop that logs every N steps.
    inner = 1 if steps <= 3 else 5
    times = []
    for _ in range(max(steps // inner, 3)):
        t0 = time.perf_counter()
        for _ in range(inner):
            state, m = step_fn(state, data)
        sync(m["loss"])
        times.append((time.perf_counter() - t0) / inner)

    dt = statistics.median(times)
    tokens_per_sec = batch * seq / dt
    fpt = flops_per_token(config, seq)
    mfu = tokens_per_sec * fpt / peak_flops
    loss = round(float(jax.device_get(m["loss"])), 4)
    del state, m, data, step_fn, opt
    jax.clear_caches()
    return {
        "tokens_per_sec": tokens_per_sec,
        "mfu": mfu,
        "step_time_s": dt,
        "loss": loss,
        "batch": batch,
        "seq": seq,
        "opt_bits": opt_bits,
        "grad_accum": grad_accum,
        "loss_impl": loss_impl,
    }


def _bench(quick: bool = False) -> dict:
    import jax

    from dstack_tpu.models import llama

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    if on_tpu:
        config = llama.LLAMA_32_1B
        # batch 8 saturates the MXU on a single v5e chip (measured:
        # batch 4 → 0.37 MFU, batch 8 → 0.42; batch 16 exceeds HBM
        # with f32 Adam state — int8 state lifts that wall, see
        # DTPU_BENCH_* knobs + tools/roofline_levers.py)
        batch, seq = 8, 1024
        steps = 10 if quick else 20
        peak_flops = 197e12  # v5e bf16 per chip
    else:
        config = llama.LLAMA_TINY
        batch, seq = 4, 128
        steps = 3
        peak_flops = 1e12  # nominal; CPU numbers are smoke-test only

    # roofline-lever knobs (official variants; the headline default
    # stays the honest accum=1/f32 per-step measurement until a lever
    # is proven ≥ on hardware, then the capture records both)
    batch = int(os.environ.get("DTPU_BENCH_BATCH", batch))
    opt_bits = int(os.environ.get("DTPU_BENCH_OPT_BITS", "32"))
    grad_accum = int(os.environ.get("DTPU_BENCH_GRAD_ACCUM", "1"))
    loss_impl = os.environ.get("DTPU_BENCH_LOSS_IMPL", "fused")

    n_chips = 1  # bench runs per-chip; multi-chip scaling via dryrun/tests
    t = train_bench(
        config=config, batch=batch, seq=seq, steps=steps,
        peak_flops=peak_flops, opt_bits=opt_bits, grad_accum=grad_accum,
        loss_impl=loss_impl,
    )
    dt = t["step_time_s"]
    tokens_per_sec_per_chip = t["tokens_per_sec"] / n_chips
    mfu = t["mfu"]
    loss = t["loss"]
    # serving measurement (decode tok/s + TTFT) rides along in extra —
    # the driver records ONE line, so both numbers live on it. The
    # training state (params + Adam moments, ~15GB f32 for the 1B
    # model) was freed by train_bench or the serving engine's second
    # param copy + KV cache OOMs a 16GB v5e chip.
    try:
        from dstack_tpu.serve.bench import run_bench as serve_bench

        if on_tpu:
            # batch 16 + turbo 128 measured best on v5e through the
            # tunneled driver (batch 32/64 regress: the masked
            # full-cache attention read grows linearly with slots);
            # turbo_depth chains macro-steps per host round trip —
            # overridable while the latency matrix settles its default
            serve_model = "llama-3.2-1b"
            serve = serve_bench(
                model=serve_model, batch=16, max_seq=1024,
                prompt_len=256, gen_len=64 if quick else 128,
                turbo_steps=128,
                turbo_depth=int(
                    os.environ.get("DTPU_BENCH_TURBO_DEPTH", "1")
                ),
            )
        else:
            serve_model = "llama-tiny"
            # prefill_chunk 32 so the 128-token long-prompt pair still
            # spans >=1 reusable chunk — with the engine's default 256
            # the prefix-cache TTFT pair is structurally null on CPU
            serve = serve_bench(
                model=serve_model, batch=2, max_seq=256,
                prompt_len=64, gen_len=8, prefill_chunk=32,
            )
        serve_extra = {
            "decode_tokens_per_sec": serve["value"],
            "ttft_ms_p50": serve["extra"]["ttft_ms_p50"],
            # prefix caching: 2×-length prompt pair, cold vs hit
            "ttft_long_cold_ms": serve["extra"].get("ttft_long_cold_ms"),
            "ttft_prefix_hit_ms": serve["extra"].get("ttft_prefix_hit_ms"),
            "model": serve_model,
        }
    except Exception as e:  # serving must not sink the training number
        serve_extra = {"error": f"{type(e).__name__}: {e}"}
    return {
        "metric": f"train_tokens_per_sec_per_chip[{_config_name(config)},bf16,{backend}]",
        "value": round(tokens_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 3),
        "extra": {
            "mfu": round(mfu, 4),
            "step_time_s": round(dt, 4),
            "batch": batch,
            "seq": seq,
            "loss": loss,
            "params_b": round(config.num_params() / 1e9, 3),
            "opt_bits": opt_bits,
            "grad_accum": grad_accum,
            "serve": serve_extra,
        },
    }


def _config_name(config) -> str:
    from dstack_tpu.models import llama

    for name, c in llama.CONFIGS.items():
        if c == config:
            return name
    return "custom"


def _run_tpu_child(quick: bool) -> dict:
    """Run the TPU measurement in a WATCHDOG subprocess: a tunnel that
    dies mid-bench would otherwise hang this process forever and lose
    even the CPU fallback line. Returns the child's JSON, or raises."""
    timeout = 900 if quick else 1800
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_child"]
        + (["--quick"] if quick else []),
        timeout=timeout, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"TPU bench child failed: {proc.stderr.strip()[-300:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    quick = "--quick" in sys.argv
    if "--_child" in sys.argv:  # the watchdogged TPU measurement
        print(json.dumps(_bench(quick=quick)))
        return
    result = None
    # Total patience before conceding to CPU: tunnel outages observed in
    # rounds 2/3 cost the driver-captured TPU number both times. 20 min
    # of retry (env-overridable) is cheap next to losing the round's
    # only hardware datapoint.
    budget_s = float(os.environ.get("DTPU_BENCH_TPU_WAIT_S", "1200"))
    deadline = time.monotonic() + budget_s
    attempt_notes = []
    for attempt in range(3):  # full bench attempts, each behind a probe
        wait = _wait_for_tpu(budget_s=max(0.0, deadline - time.monotonic()))
        if not wait["ok"]:
            attempt_notes.append(
                f"probe gave up after {wait['attempts']} tries / "
                f"{wait['waited_s']}s"
            )
            break
        try:
            result = _run_tpu_child(quick)
            break
        except Exception as e:
            detail = str(e).strip()[:300] or type(e).__name__
            attempt_notes.append(f"attempt {attempt + 1} died: {detail}")
            if time.monotonic() >= deadline:
                break
    if result is None:
        import glob

        evidence = sorted(
            glob.glob(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "BENCH_TPU_r*_evidence.json"))
        )
        ev_note = (
            f" Last TPU evidence: {os.path.basename(evidence[-1])}"
            if evidence else ""
        )
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
            result = _bench(quick=quick)
            # the SHARED artifact labeler (utils/backend.py) phrases
            # the unreachable note so every bench/soak artifact says
            # it the same way
            from dstack_tpu.utils.backend import backend_info

            info = backend_info(
                requested="tpu",
                detail=(
                    f"bench died or {'; '.join(attempt_notes)}; waited "
                    f"up to {budget_s:.0f}s with retries"
                ),
            )
            result["backend"] = info["backend"]
            result["note"] = (info["note"] or "") + ev_note
        except Exception as e:  # always print a line; the driver records it
            result = {
                "metric": "train_tokens_per_sec_per_chip",
                "value": 0.0,
                "unit": "tokens/s/chip",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
            }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
